"""Weighted linear SVM (squared hinge, one-vs-rest) base learner.

Spark ML ships ``LinearSVC`` as a stock Predictor, so the reference's
plugin slot accepts it directly [B:5, SURVEY §1 L3]. The TPU-native
learner minimizes the *squared* hinge — smooth, so a damped Newton
solver applies — one-vs-rest over classes (Spark's LinearSVC is
binary-only; OVR is the strict superset sklearn uses).

Newton structure is friendlier than multinomial logistic: OVR decouples
classes, so the Hessian is block-diagonal — ``C`` independent
``(d, d)`` systems, each an indicator-weighted Gram
``Xᵀ diag(2w·1[margin<1]) X`` (one MXU matmul per class) solved by a
batched Cholesky. No ``(C·d)²`` coupling matrix exists at any point.

``sample_weight`` carries exact Poisson multiplicities and every row
reduction goes through ``maybe_psum``, so data-sharded fits return the
single-device solution bit-for-bit [SURVEY §7 hard-part 2, §5 comms].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_bagging_tpu.models.base import (
    Aux,
    BaseLearner,
    Params,
    PooledStartMixin,
    augment_bias,
)
from spark_bagging_tpu.ops.reduce import maybe_psum

# Same rationale as logistic._SOLVER_DAMPING: solve-time Levenberg
# damping keeps the (possibly rank-deficient, e.g. no active rows for a
# class) per-class Gram positive definite; the gradient stays exact.
# (It covers the bias row too — no separate bias jitter is needed.)
_SOLVER_DAMPING = 1e-3
# Step-halving candidates for the Newton line search. The squared hinge
# is piecewise quadratic: a full step can overshoot the active-set
# boundary and cycle permanently (observed: loss 0.21→21.8→0.37→0.21 on
# a 12-row bag — exactly the small-effective-n regime Poisson bootstrap
# produces). Trying halved steps and keeping the best, with 0 as a
# floor, makes the iteration monotonically non-increasing.
_STEPS = (1.0, 0.5, 0.25, 0.0)



class LinearSVC(PooledStartMixin, BaseLearner):
    """L2-regularized squared-hinge linear classifier (OVR).

    Parameters mirror the Spark/sklearn vocabulary: ``l2`` penalty
    strength (sklearn's ``C`` ≈ ``1 / (l2·n)``), ``max_iter`` static
    Newton iterations (squared hinge is piecewise quadratic — Newton
    settles in a handful), ``precision`` the MXU matmul precision.
    """

    task = "classification"
    streamable = True

    def __init__(
        self,
        l2: float = 1e-3,
        max_iter: int = 8,
        precision: str = "high",
        init: str = "zeros",
        pooled_iter: int = 5,
    ):
        if max_iter < 1:
            raise ValueError(f"max_iter must be >= 1, got {max_iter}")
        self.l2 = l2
        self.max_iter = max_iter
        self.precision = precision
        # squared-hinge OVR is convex, so the pooled warm start applies.
        # Ignored by fit_stream (no pooled pre-pass in the streaming
        # engine) — in-memory fits only.
        self.validate_init(init)
        self.init = init
        self.pooled_iter = pooled_iter

    def init_params(self, key, n_features, n_outputs):
        del key  # deterministic zero start
        return {"W": jnp.zeros((n_features + 1, n_outputs), jnp.float32)}

    def predict_scores(self, params, X):
        """OVR margins ``(n, C)`` — argmax gives the class; the vote
        aggregator's softmax is a monotone surrogate for soft voting."""
        return augment_bias(X.astype(params["W"].dtype)) @ params["W"]

    def flops_per_fit(self, n_rows, n_features, n_outputs):
        n, d, C = n_rows, n_features + 1, n_outputs
        # per iter: margins + gradient + line-search delta matmuls
        # (candidates are priced from M − s·D, no extra matmuls),
        # C indicator-weighted (d, d) Grams, C Cholesky solves
        per_iter = 6 * n * d * C + 2 * n * d * d * C + C * d**3 / 3
        return float(self.max_iter * per_iter)

    # -- streaming contract (out-of-core engine, streaming.py) ---------

    def sgd_step_flops(self, chunk_rows, n_features, n_outputs):
        return float(6 * chunk_rows * (n_features + 1) * n_outputs)

    def row_loss(self, params, X, y):
        M = self.predict_scores(params, X)
        T = 2.0 * jax.nn.one_hot(y, M.shape[1], dtype=M.dtype) - 1.0
        a = jax.nn.relu(1.0 - T * M)
        return jnp.sum(a * a, axis=1)

    def penalty(self, params):
        return 0.5 * self.l2 * jnp.sum(params["W"][:-1] ** 2)

    # ------------------------------------------------------------------

    def fit(self, params, X, y, sample_weight, key, *, axis_name=None,
            prepared=None):
        del key, prepared  # deterministic solver; no precomputation
        Xb = augment_bias(X.astype(jnp.float32))
        w = sample_weight.astype(jnp.float32)
        # floor: all-zero bootstrap draws must stay finite
        # (round-4 audit; see linear.py)
        w_sum = jnp.maximum(maybe_psum(jnp.sum(w), axis_name), 1e-12)
        d = Xb.shape[1]
        C = params["W"].shape[1]
        # L2 on feature rows only; the bias row is conditioned by the
        # solver damping below.
        pen = jnp.concatenate(
            [jnp.full((d - 1,), self.l2, jnp.float32),
             jnp.zeros((1,), jnp.float32)]
        )
        T = 2.0 * jax.nn.one_hot(y, C, dtype=jnp.float32) - 1.0

        with jax.default_matmul_precision(self.precision):

            def data_loss_at(M):
                """Weighted squared-hinge mass from precomputed margins."""
                a = jax.nn.relu(1.0 - T * M)
                return maybe_psum(
                    jnp.sum(w[:, None] * a * a), axis_name
                ) / w_sum

            def step(W, _):
                M = Xb @ W                               # (n, C)
                a = jax.nn.relu(1.0 - T * M)
                loss = maybe_psum(
                    jnp.sum(w[:, None] * a * a), axis_name
                ) / w_sum + 0.5 * self.l2 * jnp.sum(W[:-1] ** 2)
                # gradient: d/dW Σ w·a² = Xᵀ(−2w·T·a), penalty added
                # outside the psum (it is replicated, not sharded)
                G = maybe_psum(
                    Xb.T @ (-2.0 * w[:, None] * T * a), axis_name
                ) / w_sum
                G = G + jnp.concatenate(
                    [self.l2 * W[:-1], jnp.zeros((1, C), W.dtype)]
                )
                # per-class Hessian: Xᵀ diag(2w·1[a>0]) X — C (d, d)
                # Grams, static Python loop (C is a trace-time constant)
                active = (a > 0).astype(jnp.float32) * (2.0 * w[:, None])
                H = jnp.stack(
                    [(Xb * active[:, c:c + 1]).T @ Xb for c in range(C)]
                ) / w_sum
                H = maybe_psum(H, axis_name)
                H = H + jnp.diag(pen)[None] \
                    + _SOLVER_DAMPING * jnp.eye(d, dtype=jnp.float32)[None]
                delta = jax.vmap(
                    lambda Hc, gc: jax.scipy.linalg.solve(
                        Hc, gc, assume_a="pos"
                    )
                )(H, G.T).T                              # (d, C)
                # Step-halving line search over _STEPS (see above):
                # margins at W − s·delta are M − s·D, so ONE extra
                # matmul (D) prices every candidate; 0 is among them,
                # so the loss never increases.
                D = Xb @ delta
                cand_loss = jnp.stack([
                    data_loss_at(M - s * D)
                    + 0.5 * self.l2 * jnp.sum((W - s * delta)[:-1] ** 2)
                    for s in _STEPS
                ])
                s_best = jnp.asarray(_STEPS)[jnp.argmin(cand_loss)]
                return W - s_best * delta, loss

            W, losses = jax.lax.scan(
                step, params["W"], None, length=self.max_iter
            )
            # final loss at the returned iterate (the scan reports the
            # loss *before* each step)
            final = data_loss_at(Xb @ W) \
                + 0.5 * self.l2 * jnp.sum(W[:-1] ** 2)
        return {"W": W}, {"loss": final, "loss_curve": losses}
