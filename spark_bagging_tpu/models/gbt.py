"""Gradient-boosted trees — Spark ML ``GBTClassifier``/``GBTRegressor``.

Spark ships GBTs as stock Predictors the reference can bag [B:5,
SURVEY §1 L3]. The TPU-native formulation is Newton boosting over the
existing static-shape tree machinery (models/tree.py): every round
grows one depth-bounded tree on the current pseudo-residuals, and the
whole boosting loop is a ``lax.scan`` — one traced round body, M
iterations, no Python-side dynamism — so a full GBT fit jits and
``vmap``s over bagging replicas like any other learner.

The reduction to the existing tree engine is exact: Newton boosting
fits each tree to targets ``z = −g/h`` under row weights ``h`` (the
per-row loss Hessian). The regression tree's weighted-SSE split
criterion on ``(h, h·z)`` is then precisely the XGBoost-style gain
``G_L²/H_L + G_R²/H_R`` (the ``Σ g²/h`` term is split-invariant), and
the weighted-mean leaf value is the Newton step ``−G/H``. Quantile bin
edges are computed ONCE (`prepare`) and shared by all rounds and all
replicas — the histogram-GBT standard.

Per-round FLOPs are the tree's level contractions (MXU matmuls / the
Pallas fused kernel); sample weights carry exact Poisson bootstrap
multiplicities through ``h``; every row reduction rides ``maybe_psum``
[SURVEY §7 hard-part 2, §5 comms].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_bagging_tpu.models.tree import DecisionTreeRegressor, _EPS
from spark_bagging_tpu.ops.reduce import maybe_psum

_HESS_FLOOR = 1e-6  # saturated sigmoid ⇒ h→0; floor keeps z=−g/h finite


class _GBTBase(DecisionTreeRegressor):
    """Shared boosting engine (see module docstring).

    Parameters mirror Spark's: ``n_rounds`` (maxIter), ``lr``
    (stepSize), ``max_depth``, ``subsample`` (subsamplingRate — each
    round trains on an independent Bernoulli row subset drawn from the
    round key, the stochastic-gradient-boosting regularizer), plus the
    tree engine's ``n_bins`` / ``split_impl`` / ``feature_subset``
    knobs.
    """

    streamable = False  # structure search per round, like the trees
    # NOT tree-streamable: fitted params are R stacked trees + f0, not
    # the single tree the tree-stream engine grows — routing there
    # would fit the wrong model and crash predict (params mismatch)
    tree_streamable = False

    def __init__(
        self,
        n_rounds: int = 20,
        max_depth: int = 5,
        lr: float = 0.1,
        subsample: float = 1.0,
        n_bins: int = 32,
        hist_dtype: str = "bfloat16",
        precision: str = "highest",
        split_impl: str = "auto",
        feature_subset: str | float | int | None = None,
    ):
        super().__init__(
            max_depth, n_bins, hist_dtype, precision, split_impl,
            feature_subset,
        )
        if n_rounds < 1:
            raise ValueError(f"n_rounds must be >= 1, got {n_rounds}")
        if not 0.0 < subsample <= 1.0:
            raise ValueError(
                f"subsample must be in (0, 1], got {subsample}"
            )
        self.n_rounds = n_rounds
        self.lr = lr
        self.subsample = subsample

    # -- per-task hooks -------------------------------------------------

    def _init_margin(self, y, w, w_sum, axis_name):
        raise NotImplementedError

    def _pseudo(self, y, F, w):
        """(h, z): Newton row weights and targets at margin F."""
        raise NotImplementedError

    def _round_loss(self, y, F, w, w_sum, axis_name):
        raise NotImplementedError

    # -- BaseLearner contract ------------------------------------------

    def init_params(self, key, n_features, n_outputs):
        del key, n_outputs
        M = 2**self.max_depth - 1
        L = 2**self.max_depth
        R = self.n_rounds
        return {
            "f0": jnp.zeros((), jnp.float32),
            # flat (R·M,) so the bagging-level feature_importances_
            # reads gains/features exactly as it does for single trees
            "feature": jnp.zeros((R * M,), jnp.int32),
            "threshold": jnp.zeros((R * M,), jnp.float32),
            "gain": jnp.zeros((R * M,), jnp.float32),
            "leaf": jnp.zeros((R, L), jnp.float32),
        }

    def flops_per_fit(self, n_rows, n_features, n_outputs):
        del n_outputs
        # every round contracts K=3 moment stats (h, h·z, h·z²)
        # regardless of task — the inherited tree model would undercount
        # the classifier by K=2/3
        nodes_total = 2**self.max_depth - 1
        one_tree = 2 * n_rows * n_features * self.n_bins * 3 * nodes_total
        return float(self.n_rounds * one_tree)

    def fit(self, params, X, y, sample_weight, key, *, axis_name=None,
            prepared=None):
        del params
        if self.subsample < 1.0 and key is None:
            raise ValueError(
                "subsample < 1 draws per-round row subsets from the "
                "replica fit key; fit was called with key=None"
            )
        if prepared is None:
            prepared = self.prepare(X, axis_name=axis_name)
        yf = y.astype(jnp.float32)
        w = sample_weight.astype(jnp.float32)
        w_sum = maybe_psum(jnp.sum(w), axis_name)
        f0 = self._init_margin(yf, w, w_sum, axis_name)
        n = X.shape[0]

        def round_body(F, m):
            h, z = self._pseudo(yf, F, w)
            key_m = (
                jax.random.fold_in(key, m) if key is not None else None
            )
            if self.subsample < 1.0:
                # stochastic GBT: this round sees an independent
                # Bernoulli row subset; dropped rows carry zero weight
                # through every split statistic and leaf sum
                mask_key = jax.random.fold_in(key_m, 0x5B)
                if axis_name is not None:
                    # per-row sharded draws must decorrelate shards
                    # (the ensemble.py/tree_stream.py convention) —
                    # every shard holds different rows, so an identical
                    # local keep pattern would bias the subset
                    mask_key = jax.random.fold_in(
                        mask_key, jax.lax.axis_index(axis_name)
                    )
                keep = (
                    jax.random.uniform(mask_key, (h.shape[0],))
                    < self.subsample
                ).astype(jnp.float32)
                h = h * keep
            S = jnp.stack([h, h * z, h * z * z], axis=1)
            feat, thr, gain, node, _curve = self._grow(
                X, S, prepared, axis_name, key_m
            )
            stats = self._leaf_stats(node, S, axis_name)   # (L, 3)
            # Newton leaf step −G/H == weighted mean of z under h;
            # empty leaves emit 0 (no update), not a global fallback
            leaf = jnp.where(
                stats[:, 0] > 0,
                stats[:, 1] / jnp.maximum(stats[:, 0], _EPS),
                0.0,
            )
            F = F + self.lr * leaf[node]
            loss = self._round_loss(yf, F, w, w_sum, axis_name)
            return F, (feat, thr, gain, leaf, loss)

        F0 = jnp.full((n,), f0, jnp.float32)
        _, (feats, thrs, gains, leaves, losses) = jax.lax.scan(
            round_body, F0, jnp.arange(self.n_rounds)
        )
        new = {
            "f0": f0,
            "feature": feats.reshape(-1),
            "threshold": thrs.reshape(-1),
            "gain": gains.reshape(-1).astype(jnp.float32),
            "leaf": leaves.astype(jnp.float32),
        }
        return new, {"loss": losses[-1], "loss_curve": losses}

    def _margin(self, params, X):
        """Σ_m lr·leaf_m[route_m(x)] + f0 via a scan over rounds."""
        M = 2**self.max_depth - 1
        R = self.n_rounds
        feats = params["feature"].reshape(R, M)
        thrs = params["threshold"].reshape(R, M)
        leaves = params["leaf"]

        def one_round(acc, xs):
            f, t, lv = xs
            rel = self._route({"feature": f, "threshold": t}, X)
            return acc + self.lr * lv[rel], None

        acc0 = jnp.full((X.shape[0],), params["f0"], jnp.float32)
        total, _ = jax.lax.scan(one_round, acc0, (feats, thrs, leaves))
        return total


class GBTRegressor(_GBTBase):
    """Least-squares Newton boosting (h = w, z = residual)."""

    task = "regression"

    def _init_margin(self, y, w, w_sum, axis_name):
        return maybe_psum(jnp.sum(w * y), axis_name) / w_sum

    def _pseudo(self, y, F, w):
        return w, y - F

    def _round_loss(self, y, F, w, w_sum, axis_name):
        return maybe_psum(jnp.sum(w * (y - F) ** 2), axis_name) / w_sum

    def predict_scores(self, params, X):
        return self._margin(params, X)


class GBTClassifier(_GBTBase):
    """Binary logistic Newton boosting (Spark GBTClassifier is also
    binary-only). ``predict_scores`` returns ``(n, 2)`` logits
    ``[0, margin]`` so softmax reproduces the sigmoid probabilities
    for the ensemble's soft voting."""

    task = "classification"

    def init_params(self, key, n_features, n_outputs):
        if n_outputs != 2:
            raise ValueError(
                f"GBTClassifier is binary-only (got {n_outputs} "
                "classes), matching Spark ML's GBTClassifier"
            )
        return super().init_params(key, n_features, n_outputs)

    def _init_margin(self, y, w, w_sum, axis_name):
        p = jnp.clip(
            maybe_psum(jnp.sum(w * y), axis_name) / w_sum, 1e-6, 1 - 1e-6
        )
        return jnp.log(p / (1.0 - p))

    def _pseudo(self, y, F, w):
        p = jax.nn.sigmoid(F)
        h_unit = jnp.maximum(p * (1.0 - p), _HESS_FLOOR)
        return w * h_unit, (y - p) / h_unit

    def _round_loss(self, y, F, w, w_sum, axis_name):
        # weighted mean logistic loss: softplus(F) − y·F
        return maybe_psum(
            jnp.sum(w * (jax.nn.softplus(F) - y * F)), axis_name
        ) / w_sum

    def predict_scores(self, params, X):
        m = self._margin(params, X)
        return jnp.stack([jnp.zeros_like(m), m], axis=1)
