"""Gradient-boosted trees — Spark ML ``GBTClassifier``/``GBTRegressor``.

Spark ships GBTs as stock Predictors the reference can bag [B:5,
SURVEY §1 L3]. The TPU-native formulation is Newton boosting over the
existing static-shape tree machinery (models/tree.py): every round
grows one depth-bounded tree on the current pseudo-residuals, and the
whole boosting loop is a ``lax.scan`` — one traced round body, M
iterations, no Python-side dynamism — so a full GBT fit jits and
``vmap``s over bagging replicas like any other learner.

The reduction to the existing tree engine is exact: Newton boosting
fits each tree to targets ``z = −g/h`` under row weights ``h`` (the
per-row loss Hessian). The regression tree's weighted-SSE split
criterion on ``(h, h·z)`` is then precisely the XGBoost-style gain
``G_L²/H_L + G_R²/H_R`` (the ``Σ g²/h`` term is split-invariant), and
the weighted-mean leaf value is the Newton step ``−G/H``. Quantile bin
edges are computed ONCE (`prepare`) and shared by all rounds and all
replicas — the histogram-GBT standard.

Per-round FLOPs are the tree's level contractions (MXU matmuls / the
Pallas fused kernel); sample weights carry exact Poisson bootstrap
multiplicities through ``h``; every row reduction rides ``maybe_psum``
[SURVEY §7 hard-part 2, §5 comms].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_bagging_tpu.models.tree import DecisionTreeRegressor, _EPS
from spark_bagging_tpu.ops.reduce import maybe_psum

_HESS_FLOOR = 1e-6  # saturated sigmoid ⇒ h→0; floor keeps z=−g/h finite


class _GBTBase(DecisionTreeRegressor):
    """Shared boosting engine (see module docstring).

    Parameters mirror Spark's: ``n_rounds`` (maxIter), ``lr``
    (stepSize), ``max_depth``, ``subsample`` (subsamplingRate — each
    round trains on an independent Bernoulli row subset drawn from the
    round key, the stochastic-gradient-boosting regularizer), plus the
    tree engine's ``n_bins`` / ``split_impl`` / ``feature_subset``
    knobs.
    """

    streamable = False  # structure search per round, like the trees
    # NOT tree-streamable: fitted params are R stacked trees + f0, not
    # the single tree the tree-stream engine grows — routing there
    # would fit the wrong model and crash predict (params mismatch)
    tree_streamable = False

    def __init__(
        self,
        n_rounds: int = 20,
        max_depth: int = 5,
        lr: float = 0.1,
        subsample: float = 1.0,
        n_bins: int = 32,
        hist_dtype: str = "bfloat16",
        precision: str = "highest",
        split_impl: str = "auto",
        feature_subset: str | float | int | None = None,
    ):
        super().__init__(
            max_depth, n_bins, hist_dtype, precision, split_impl,
            feature_subset,
            # pre-pruning gates stay OFF for boosting: GBT split stats
            # carry Newton Hessian mass (h = w·p(1−p), near the 1e-6
            # floor for confident rounds), not row counts — a mass
            # threshold would silently leaf-ify live nodes
            min_info_gain=0.0,
            min_instances_per_node=0.0,
        )
        if n_rounds < 1:
            raise ValueError(f"n_rounds must be >= 1, got {n_rounds}")
        if not 0.0 < lr <= 1.0:  # Spark's stepSize bound — lr=0 would
            # silently train a constant model, negative lr anti-learns
            raise ValueError(f"lr must be in (0, 1], got {lr}")
        if not 0.0 < subsample <= 1.0:
            raise ValueError(
                f"subsample must be in (0, 1], got {subsample}"
            )
        self.n_rounds = n_rounds
        self.lr = lr
        self.subsample = subsample

    # -- shared round machinery ----------------------------------------

    def _validate_fit_key(self, key) -> None:
        if self.subsample < 1.0 and key is None:
            raise ValueError(
                "subsample < 1 draws per-round row subsets from the "
                "replica fit key; fit was called with key=None"
            )

    @staticmethod
    def _newton_leaf(stats):
        """Leaf Newton step −G/H == weighted mean of z under h; empty
        leaves emit 0 (no update). THE single home of the leaf policy —
        binary and multiclass engines must never diverge here."""
        return jnp.where(
            stats[:, 0] > 0,
            stats[:, 1] / jnp.maximum(stats[:, 0], _EPS),
            0.0,
        )

    def _round_row_mask(self, key_m, n, axis_name):
        """Stochastic-GBT keep mask for one round (None when
        subsample == 1). THE single home of the draw schedule: the
        0x5B fold and the per-shard axis_index decorrelation — binary
        and multiclass fits must never diverge here."""
        if self.subsample >= 1.0:
            return None
        mask_key = jax.random.fold_in(key_m, 0x5B)
        if axis_name is not None:
            # per-row sharded draws must decorrelate shards
            # (the ensemble.py/tree_stream.py convention) — every
            # shard holds different rows, so an identical local keep
            # pattern would bias the subset
            mask_key = jax.random.fold_in(
                mask_key, jax.lax.axis_index(axis_name)
            )
        return (
            jax.random.uniform(mask_key, (n,)) < self.subsample
        ).astype(jnp.float32)

    # -- per-task hooks -------------------------------------------------

    def _init_margin(self, y, w, w_sum, axis_name):
        raise NotImplementedError

    def _pseudo(self, y, F, w):
        """(h, z): Newton row weights and targets at margin F."""
        raise NotImplementedError

    def _round_loss(self, y, F, w, w_sum, axis_name):
        raise NotImplementedError

    # -- BaseLearner contract ------------------------------------------

    def init_params(self, key, n_features, n_outputs):
        del key, n_outputs
        M = 2**self.max_depth - 1
        L = 2**self.max_depth
        R = self.n_rounds
        return {
            "f0": jnp.zeros((), jnp.float32),
            # flat (R·M,) so the bagging-level feature_importances_
            # reads gains/features exactly as it does for single trees
            "feature": jnp.zeros((R * M,), jnp.int32),
            "threshold": jnp.zeros((R * M,), jnp.float32),
            "gain": jnp.zeros((R * M,), jnp.float32),
            "leaf": jnp.zeros((R, L), jnp.float32),
        }

    def flops_per_fit(self, n_rows, n_features, n_outputs):
        del n_outputs
        # every round contracts K=3 moment stats (h, h·z, h·z²)
        # regardless of task — the inherited tree model would undercount
        # the classifier by K=2/3
        nodes_total = 2**self.max_depth - 1
        one_tree = 2 * n_rows * n_features * self.n_bins * 3 * nodes_total
        return float(self.n_rounds * one_tree)

    def to_debug_string(self, params, feature_names=None) -> str:
        """Per-round tree dumps — Spark's ``GBT*Model.toDebugString``
        analog. Slices each round's (and, for multiclass, each class's)
        node arrays out of the stacked params and renders them with the
        single-tree walker."""
        import numpy as np_

        M = 2**self.max_depth - 1
        leaf = np_.asarray(params["leaf"])
        feature = np_.asarray(params["feature"])
        threshold = np_.asarray(params["threshold"])
        multiclass = leaf.ndim == 3
        R = leaf.shape[0]
        C = leaf.shape[1] if multiclass else 1
        f0 = np_.asarray(params["f0"])
        out = [
            f"{type(self).__name__} (rounds={R}, depth={self.max_depth},"
            f" lr={self.lr}, f0={np_.round(f0, 4).tolist()})"
        ]
        for r in range(R):
            for c in range(C):
                i = (r * C + c) * M
                sub = {
                    "feature": feature[i:i + M],
                    "threshold": threshold[i:i + M],
                    "leaf_value": leaf[r, c] if multiclass else leaf[r],
                }
                title = (
                    f"Tree {r} (class {c}):" if multiclass
                    else f"Tree {r}:"
                )
                body = super().to_debug_string(sub, feature_names)
                out.append(title)
                out.append("\n".join(body.split("\n")[1:]))  # drop header
        return "\n".join(out)

    def fit_workset_bytes(self, n_rows, n_features, n_outputs):
        # per-round regression-tree temps (K=3 moments; buffers reuse
        # across the scanned rounds): the (n, N·3) row-stat operand,
        # the (F, B, N, 3) f32 histogram + its right copy, the (n, 2^d)
        # leaf one-hot [round-4 audit — mirrors DecisionTree's model],
        # ×C concurrent trees for the class-vmapped multiclass engine,
        # + the (n, C) running-score state
        hist_bytes = 2 if self.hist_dtype == "bfloat16" else 4
        N = 2 ** (self.max_depth - 1)
        per_tree = (
            hist_bytes * n_rows * N * 3
            + 2 * 4.0 * n_features * self.n_bins * N * 3
            + 4.0 * n_rows * (2 ** self.max_depth)
            + 8 * n_rows
        )
        n_trees = (
            n_outputs
            if self.task == "classification" and n_outputs > 2 else 1
        )
        return float(
            per_tree * n_trees + 4 * n_rows * max(1, n_outputs)
        )

    def fit(self, params, X, y, sample_weight, key, *, axis_name=None,
            prepared=None):
        del params
        self._validate_fit_key(key)
        if prepared is None:
            prepared = self.prepare(X, axis_name=axis_name)
        yf = y.astype(jnp.float32)
        w = sample_weight.astype(jnp.float32)
        # _EPS guard: an all-zero bootstrap draw (probability e^-λ per
        # replica at small max_samples) would make f0 = 0/0 = NaN and
        # poison the whole bagged ensemble's mean vote — the single
        # trees guard their w_tot the same way (round-4 audit)
        w_sum = jnp.maximum(maybe_psum(jnp.sum(w), axis_name), _EPS)
        f0 = self._init_margin(yf, w, w_sum, axis_name)
        n = X.shape[0]

        def round_body(F, m):
            h, z = self._pseudo(yf, F, w)
            key_m = (
                jax.random.fold_in(key, m) if key is not None else None
            )
            keep = self._round_row_mask(key_m, h.shape[0], axis_name)
            if keep is not None:
                # stochastic GBT: this round sees an independent
                # Bernoulli row subset; dropped rows carry zero weight
                # through every split statistic and leaf sum
                h = h * keep
            S = jnp.stack([h, h * z, h * z * z], axis=1)
            feat, thr, gain, node, _curve = self._grow(
                X, S, prepared, axis_name, key_m
            )
            stats = self._leaf_stats(node, S, axis_name)   # (L, 3)
            leaf = self._newton_leaf(stats)
            F = F + self.lr * leaf[node]
            loss = self._round_loss(yf, F, w, w_sum, axis_name)
            return F, (feat, thr, gain, leaf, loss)

        F0 = jnp.full((n,), f0, jnp.float32)
        _, (feats, thrs, gains, leaves, losses) = jax.lax.scan(
            round_body, F0, jnp.arange(self.n_rounds)
        )
        new = {
            "f0": f0,
            "feature": feats.reshape(-1),
            "threshold": thrs.reshape(-1),
            "gain": gains.reshape(-1).astype(jnp.float32),
            "leaf": leaves.astype(jnp.float32),
        }
        return new, {"loss": losses[-1], "loss_curve": losses}

    def _margin(self, params, X):
        """Σ_m lr·leaf_m[route_m(x)] + f0 via a scan over rounds."""
        M = 2**self.max_depth - 1
        R = self.n_rounds
        feats = params["feature"].reshape(R, M)
        thrs = params["threshold"].reshape(R, M)
        leaves = params["leaf"]

        def one_round(acc, xs):
            f, t, lv = xs
            rel = self._route({"feature": f, "threshold": t}, X)
            return acc + self.lr * lv[rel], None

        acc0 = jnp.full((X.shape[0],), params["f0"], jnp.float32)
        total, _ = jax.lax.scan(one_round, acc0, (feats, thrs, leaves))
        return total


class GBTRegressor(_GBTBase):
    """Least-squares Newton boosting (h = w, z = residual)."""

    task = "regression"

    def _init_margin(self, y, w, w_sum, axis_name):
        return maybe_psum(jnp.sum(w * y), axis_name) / w_sum

    def _pseudo(self, y, F, w):
        return w, y - F

    def _round_loss(self, y, F, w, w_sum, axis_name):
        return maybe_psum(jnp.sum(w * (y - F) ** 2), axis_name) / w_sum

    def predict_scores(self, params, X):
        return self._margin(params, X)


class GBTClassifier(_GBTBase):
    """Logistic / multinomial Newton boosting.

    Binary problems use one margin tree per round (Spark GBTClassifier
    semantics; ``predict_scores`` returns ``(n, 2)`` logits
    ``[0, margin]`` so softmax reproduces the sigmoid). Multiclass
    problems — beyond Spark's binary-only GBT — grow C trees per round
    (diagonal-Newton multinomial boosting), batched over classes with
    ``vmap`` so a round is still one traced program."""

    task = "classification"

    def init_params(self, key, n_features, n_outputs):
        del key
        if n_outputs < 2:
            raise ValueError(
                f"GBTClassifier needs >= 2 classes, got {n_outputs} "
                "(a 1-class softmax would silently train a constant)"
            )
        if n_outputs == 2:
            return super().init_params(None, n_features, n_outputs)
        M = 2**self.max_depth - 1
        L = 2**self.max_depth
        R, C = self.n_rounds, n_outputs
        return {
            "f0": jnp.zeros((C,), jnp.float32),
            # flat (R·C·M,): feature_importances_ reads it unchanged
            "feature": jnp.zeros((R * C * M,), jnp.int32),
            "threshold": jnp.zeros((R * C * M,), jnp.float32),
            "gain": jnp.zeros((R * C * M,), jnp.float32),
            "leaf": jnp.zeros((R, C, L), jnp.float32),
        }

    def flops_per_fit(self, n_rows, n_features, n_outputs):
        one = super().flops_per_fit(n_rows, n_features, n_outputs)
        return one * (1 if n_outputs == 2 else n_outputs)

    # -- multiclass engine (C trees per round, vmapped over classes) ---

    def _fit_multiclass(self, params, X, y, w, key, axis_name, prepared):
        C = params["leaf"].shape[1]
        yf32 = jax.nn.one_hot(y, C, dtype=jnp.float32)       # (n, C)
        # _EPS: see the binary fit — clip(0/0) propagates the NaN
        w_sum = jnp.maximum(maybe_psum(jnp.sum(w), axis_name), _EPS)
        prior = jnp.clip(
            maybe_psum(w @ yf32, axis_name) / w_sum, 1e-6, 1.0
        )
        f0 = jnp.log(prior)                                  # (C,)
        n = X.shape[0]

        def round_body(F, m):
            p = jax.nn.softmax(F, axis=-1)                   # (n, C)
            h_unit = jnp.maximum(p * (1.0 - p), _HESS_FLOOR)
            key_m = (
                jax.random.fold_in(key, m) if key is not None else None
            )
            keep = self._round_row_mask(key_m, n, axis_name)
            wr = w if keep is None else w * keep
            h = wr[:, None] * h_unit                         # (n, C)
            z = (yf32 - p) / h_unit

            def grow_one(hc, zc, key_c):
                S = jnp.stack([hc, hc * zc, hc * zc * zc], axis=1)
                feat, thr, gain, node, _curve = self._grow(
                    X, S, prepared, axis_name, key_c
                )
                stats = self._leaf_stats(node, S, axis_name)
                leaf = self._newton_leaf(stats)
                return feat, thr, gain, leaf, leaf[node]

            # class keys live under their own tag so the class index
            # can never collide with the row-mask fold (0x5B) at C>=92
            keys_c = (
                jax.vmap(
                    lambda c: jax.random.fold_in(
                        jax.random.fold_in(key_m, 0x7EEE), c
                    )
                )(jnp.arange(C))
                if key_m is not None
                # placeholder keys — only reachable with
                # feature_subset unset (guarded in fit below), where
                # _grow never consumes its key
                else jnp.zeros((C,), jnp.uint32)
            )
            feat, thr, gain, leaf, upd = jax.vmap(grow_one)(
                h.T, z.T, keys_c
            )                                                # (C, ...)
            F = F + self.lr * upd.T
            logp = jax.nn.log_softmax(F, axis=-1)
            nll = -jnp.sum(yf32 * logp, axis=1)
            loss = maybe_psum(jnp.sum(w * nll), axis_name) / w_sum
            return F, (feat, thr, gain, leaf, loss)

        F0 = jnp.broadcast_to(f0[None, :], (n, C))
        _, (feats, thrs, gains, leaves, losses) = jax.lax.scan(
            round_body, F0, jnp.arange(self.n_rounds)
        )
        new = {
            "f0": f0,
            "feature": feats.reshape(-1),
            "threshold": thrs.reshape(-1),
            "gain": gains.reshape(-1).astype(jnp.float32),
            "leaf": leaves.astype(jnp.float32),
        }
        return new, {"loss": losses[-1], "loss_curve": losses}

    def fit(self, params, X, y, sample_weight, key, *, axis_name=None,
            prepared=None):
        if params["leaf"].ndim == 2:  # binary: scalar-margin engine
            return super().fit(
                params, X, y, sample_weight, key,
                axis_name=axis_name, prepared=prepared,
            )
        self._validate_fit_key(key)
        if key is None and self._n_split_features(X.shape[1]) is not None:
            # mirror _grow's guard BEFORE the vmap substitutes
            # placeholder keys: a zeros key would silently give every
            # class tree identical feature-subset draws
            raise ValueError(
                "feature_subset per-split sampling needs the replica "
                "fit key; fit was called with key=None"
            )
        if prepared is None:
            prepared = self.prepare(X, axis_name=axis_name)
        return self._fit_multiclass(
            params, X, y.astype(jnp.int32),
            sample_weight.astype(jnp.float32), key, axis_name, prepared,
        )

    def _margin_multiclass(self, params, X):
        M = 2**self.max_depth - 1
        R = self.n_rounds
        C = params["leaf"].shape[1]
        feats = params["feature"].reshape(R, C, M)
        thrs = params["threshold"].reshape(R, C, M)
        leaves = params["leaf"]                              # (R, C, L)

        def one_round(acc, xs):
            f, t, lv = xs

            def route_c(fc, tc, lc):
                rel = self._route({"feature": fc, "threshold": tc}, X)
                return lc[rel]

            upd = jax.vmap(route_c)(f, t, lv)                # (C, n)
            return acc + self.lr * upd.T, None

        acc0 = jnp.broadcast_to(
            params["f0"][None, :], (X.shape[0], C)
        )
        total, _ = jax.lax.scan(one_round, acc0, (feats, thrs, leaves))
        return total

    def _init_margin(self, y, w, w_sum, axis_name):
        p = jnp.clip(
            maybe_psum(jnp.sum(w * y), axis_name) / w_sum, 1e-6, 1 - 1e-6
        )
        return jnp.log(p / (1.0 - p))

    def _pseudo(self, y, F, w):
        p = jax.nn.sigmoid(F)
        h_unit = jnp.maximum(p * (1.0 - p), _HESS_FLOOR)
        return w * h_unit, (y - p) / h_unit

    def _round_loss(self, y, F, w, w_sum, axis_name):
        # weighted mean logistic loss: softplus(F) − y·F
        return maybe_psum(
            jnp.sum(w * (jax.nn.softplus(F) - y * F)), axis_name
        ) / w_sum

    def predict_scores(self, params, X):
        if params["leaf"].ndim == 3:
            return self._margin_multiclass(params, X)
        m = self._margin(params, X)
        return jnp.stack([jnp.zeros_like(m), m], axis=1)
