"""Two-layer MLP base learners — config 4 of the baseline [B:10].

The reference's MLP base learner is Spark ML's
MultilayerPerceptronClassifier (JVM L-BFGS over netlib BLAS)
[SURVEY §2b]. The TPU-native learner is a one-hidden-layer network
trained by Adam over a `lax.scan` of minibatch steps — iteration count
and batch size are static hyperparameters so the whole fit jits and
`vmap`s over replicas; each replica draws its own minibatch stream from
its folded key [SURVEY §7.7].

Bootstrap weighting: the per-replica Poisson counts multiply into the
minibatch loss (weighted-sum / weight-sum normalization), so rows a
replica never sampled (weight 0) contribute nothing — exact-multiplicity
semantics in expectation over minibatches, exact for full-batch
(``batch_size=None``) [SURVEY §7 hard-part 2].

Data sharding: gradients are summed with ``maybe_psum`` over the data
axis before normalization, so a sharded full-batch fit reproduces the
single-device update exactly [SURVEY §5 comms backend].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from spark_bagging_tpu.models.base import BaseLearner
from spark_bagging_tpu.ops.reduce import maybe_psum

_EPS = 1e-8

_ACTIVATIONS = {
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
    "gelu": jax.nn.gelu,
}


class _MLPBase(BaseLearner):
    """Shared forward/training loop for classifier/regressor MLPs."""

    streamable = True

    def __init__(
        self,
        hidden: int = 64,
        max_iter: int = 200,
        batch_size: int | None = None,
        lr: float = 1e-3,
        l2: float = 1e-4,
        activation: str = "relu",
        precision: str = "high",
    ):
        if max_iter < 1:
            raise ValueError(f"max_iter must be >= 1, got {max_iter}")
        if activation not in _ACTIVATIONS:
            raise ValueError(
                f"activation must be one of {sorted(_ACTIVATIONS)}, "
                f"got {activation!r}"
            )
        if hidden < 1:
            raise ValueError(f"hidden must be >= 1, got {hidden}")
        if batch_size is not None and batch_size < 1:
            raise ValueError(
                f"batch_size must be >= 1 or None, got {batch_size}"
            )
        self.hidden = hidden
        self.max_iter = max_iter
        self.batch_size = batch_size
        self.lr = lr
        self.l2 = l2
        self.activation = activation
        self.precision = precision

    def init_params(self, key, n_features, n_outputs):
        k1, k2 = jax.random.split(key)
        s1 = jnp.sqrt(2.0 / n_features)
        s2 = jnp.sqrt(2.0 / self.hidden)
        return {
            "W1": s1 * jax.random.normal(
                k1, (n_features, self.hidden), jnp.float32
            ),
            "b1": jnp.zeros((self.hidden,), jnp.float32),
            "W2": s2 * jax.random.normal(
                k2, (self.hidden, n_outputs), jnp.float32
            ),
            "b2": jnp.zeros((n_outputs,), jnp.float32),
        }

    def _forward(self, params, X):
        h = _ACTIVATIONS[self.activation](X @ params["W1"] + params["b1"])
        return h @ params["W2"] + params["b2"]

    def flops_per_fit(self, n_rows, n_features, n_outputs):
        b = self.batch_size if self.batch_size is not None else n_rows
        b = min(b, n_rows)
        # fwd + bwd ≈ 3x the two forward matmuls per step
        per_step = 6 * b * (n_features * self.hidden + self.hidden * n_outputs)
        return float(self.max_iter * per_step)

    def sgd_step_flops(self, chunk_rows, n_features, n_outputs):
        return float(
            6 * chunk_rows
            * (n_features * self.hidden + self.hidden * n_outputs)
        )

    def fit_workset_bytes(self, n_rows, n_features, n_outputs):
        b = min(self.batch_size or n_rows, n_rows)
        # activations + their adjoints (~3x) on one minibatch, Adam's
        # 3 param copies (params + 2 moments), the per-replica (b, d)
        # minibatch gather X[idx] (idx differs per replica under vmap —
        # at wide-feature scale this dominates the activations), and
        # the per-replica weight vector
        return float(
            12 * b * (self.hidden + n_outputs)
            + 12 * (n_features * self.hidden + self.hidden * n_outputs)
            + 4 * b * n_features
            + 4 * n_rows
        )

    def _row_loss(self, params, X, y):
        """Per-row unweighted loss ``(n,)``; task-specific."""
        raise NotImplementedError

    def _penalty(self, params):
        return 0.5 * self.l2 * (
            jnp.sum(params["W1"] ** 2) + jnp.sum(params["W2"] ** 2)
        )

    # -- streaming contract (out-of-core engine, streaming.py) ---------

    def row_loss(self, params, X, y):
        return self._row_loss(params, X.astype(jnp.float32), y)

    def penalty(self, params):
        return self._penalty(params)

    def fit(self, params, X, y, sample_weight, key, *, axis_name=None,
            prepared=None):
        # MXU precision (trace-time context): SGD tolerates lower matmul
        # precision than the closed-form solvers, so default "high"
        # (not the bf16 TPU default, which degrades convergence; not
        # "highest", which the noise-tolerant optimizer doesn't need).
        with jax.default_matmul_precision(self.precision):
            return self._fit(params, X, y, sample_weight, key,
                             axis_name=axis_name, prepared=prepared)

    def _fit(self, params, X, y, sample_weight, key, *, axis_name=None,
             prepared=None):
        del prepared
        X = X.astype(jnp.float32)
        w = sample_weight.astype(jnp.float32)
        n = X.shape[0]
        opt = optax.adam(self.lr)

        def weighted_grad(p, Xb, yb, wb):
            """(loss, grad) of the weighted mean loss + penalty; row sums
            are psum'd so data-sharded full-batch steps are exact."""
            loss_sum, grad = jax.value_and_grad(
                lambda p: jnp.sum(wb * self._row_loss(p, Xb, yb))
            )(p)
            denom = jnp.maximum(maybe_psum(jnp.sum(wb), axis_name), _EPS)
            grad = jax.tree.map(
                lambda a: maybe_psum(a, axis_name) / denom, grad
            )
            pen, pen_grad = jax.value_and_grad(self._penalty)(p)
            grad = jax.tree.map(jnp.add, grad, pen_grad)
            loss = maybe_psum(loss_sum, axis_name) / denom + pen
            return loss, grad

        # batch_size >= n degenerates to the EXACT full-batch path — a
        # with-replacement draw of n rows would silently train on ~63%
        # unique rows per step, a different (noisier) trajectory than
        # the "full batch" the size requests
        if self.batch_size is None or self.batch_size >= n:
            def step(carry, _):
                p, opt_state = carry
                loss, g = weighted_grad(p, X, y, w)
                updates, opt_state = opt.update(g, opt_state, p)
                return (optax.apply_updates(p, updates), opt_state), loss
            xs = None
        else:
            b = self.batch_size

            def step(carry, k_step):
                p, opt_state = carry
                idx = jax.random.randint(k_step, (b,), 0, n)
                loss, g = weighted_grad(p, X[idx], y[idx], w[idx])
                updates, opt_state = opt.update(g, opt_state, p)
                return (optax.apply_updates(p, updates), opt_state), loss
            xs = jax.random.split(key, self.max_iter)

        (params, _), curve = jax.lax.scan(
            step, (params, opt.init(params)), xs, length=self.max_iter
        )
        # final loss on the full (weighted) data for reporting
        w_sum = maybe_psum(jnp.sum(w), axis_name)
        full = (
            maybe_psum(jnp.sum(w * self._row_loss(params, X, y)), axis_name)
            / jnp.maximum(w_sum, _EPS)
            + self._penalty(params)
        )
        return params, {"loss": full, "loss_curve": curve}


class MLPClassifier(_MLPBase):
    """One-hidden-layer softmax classifier (2-layer MLP [B:10])."""

    task = "classification"

    def predict_scores(self, params, X):
        return self._forward(params, X.astype(jnp.float32))

    def _row_loss(self, params, X, y):
        logp = jax.nn.log_softmax(self._forward(params, X), axis=-1)
        return -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]


class MLPRegressor(_MLPBase):
    """One-hidden-layer regression MLP (squared loss)."""

    task = "regression"

    def init_params(self, key, n_features, n_outputs):
        del n_outputs  # regression heads are scalar
        return super().init_params(key, n_features, 1)

    def predict_scores(self, params, X):
        return self._forward(params, X.astype(jnp.float32))[:, 0]

    def _row_loss(self, params, X, y):
        pred = self._forward(params, X)[:, 0]
        return 0.5 * (pred - y) ** 2
