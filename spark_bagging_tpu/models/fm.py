"""Factorization machines — Spark ML ``FMClassifier``/``FMRegressor``.

Spark ships degree-2 factorization machines as stock Predictors
[B:5, SURVEY §1 L3]: ŷ(x) = w₀ + wᵀx + ½ Σ_f [(vᵀ_f x)² − Σ_i v²_if x²_i],
the pairwise-interaction model whose O(d·k) factorized form is two
matmuls — ``X @ V`` and ``X² @ V²`` — exactly the MXU shape, trained
here by a fixed-iteration full-batch Adam scan (Spark uses minibatch
gradient descent; the iteration count is static so the whole fit jits
and ``vmap``s over replicas).

Classification is multinomial: ``C`` FM score columns trained under a
coupled softmax NLL (a strict superset of Spark's binary-only
FMClassifier); softmax over the columns feeds the ensemble's soft
voting. Row reductions ride ``maybe_psum``
so data-sharded fits take the identical Adam trajectory
[SURVEY §7 hard-part 2, §5 comms].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from spark_bagging_tpu.models.base import BaseLearner
from spark_bagging_tpu.ops.reduce import maybe_psum


class _FMBase(BaseLearner):
    """Shared degree-2 FM machinery (see module docstring).

    ``factor_size`` is Spark's ``factorSize`` (the latent dim k),
    ``init_std`` the factor init scale, ``l2`` the shared penalty on
    linear weights and factors, ``max_iter``/``lr`` the Adam schedule.
    """

    streamable = True

    def __init__(
        self,
        factor_size: int = 8,
        l2: float = 1e-4,
        max_iter: int = 100,
        lr: float = 0.05,
        init_std: float = 0.01,
        precision: str = "high",
    ):
        if factor_size < 1:
            raise ValueError(
                f"factor_size must be >= 1, got {factor_size}"
            )
        if max_iter < 1:
            raise ValueError(f"max_iter must be >= 1, got {max_iter}")
        self.factor_size = factor_size
        self.l2 = l2
        self.max_iter = max_iter
        self.lr = lr
        self.init_std = init_std
        self.precision = precision

    def _n_scores(self, n_outputs: int) -> int:
        return n_outputs if self.task == "classification" else 1

    def init_params(self, key, n_features, n_outputs):
        C = self._n_scores(n_outputs)
        V = self.init_std * jax.random.normal(
            key, (n_features, self.factor_size, C), jnp.float32
        )
        return {
            "W": jnp.zeros((n_features + 1, C), jnp.float32),
            "V": V,
        }

    def flops_per_fit(self, n_rows, n_features, n_outputs):
        n, d, k = n_rows, n_features, self.factor_size
        C = self._n_scores(n_outputs)
        # forward: two (n, d)@(d, kC) matmuls + linear term; backward
        # ≈ 2x forward (standard AD accounting)
        return float(self.max_iter * 3 * (4 * n * d * k * C + 2 * n * d * C))

    def fit_workset_bytes(self, n_rows, n_features, n_outputs):
        k = self.factor_size
        C = self._n_scores(n_outputs)
        # dominant per-replica temps: the (n, k, C) XV and X2V2
        # pairwise activations (plus their AD adjoints, ~2x), the
        # (n, C) scores/probs, and the (n,) weight vector — without
        # this model auto_chunk_size keeps legacy vmap-all and a
        # 1000-replica FM bag OOMs exactly where the resolver was
        # built to step in [utils/memory.py]
        return float(
            4 * (3 * 2 * n_rows * k * C + 2 * n_rows * C + 2 * n_rows)
        )

    def sgd_step_flops(self, chunk_rows, n_features, n_outputs):
        k = self.factor_size
        C = self._n_scores(n_outputs)
        # two (n, d)@(d, kC) pairwise matmuls + the linear term; x3
        return float(
            3 * (4 * chunk_rows * n_features * k * C
                 + 2 * chunk_rows * n_features * C)
        )

    def _raw_scores(self, params, X):
        """(n, C) FM scores: linear + factorized pairwise terms."""
        X = X.astype(jnp.float32)
        W, V = params["W"], params["V"]
        d, k, C = V.shape
        lin = X @ W[:-1] + W[-1]                         # (n, C)
        Vf = V.reshape(d, k * C)
        XV = (X @ Vf).reshape(-1, k, C)                  # (n, k, C)
        X2V2 = ((X * X) @ (Vf * Vf)).reshape(-1, k, C)
        return lin + 0.5 * jnp.sum(XV * XV - X2V2, axis=1)

    def penalty(self, params):
        return 0.5 * self.l2 * (
            jnp.sum(params["W"][:-1] ** 2) + jnp.sum(params["V"] ** 2)
        )

    def fit(self, params, X, y, sample_weight, key, *, axis_name=None,
            prepared=None):
        del key, prepared
        w = sample_weight.astype(jnp.float32)
        # floor: all-zero bootstrap draws must stay finite
        # (round-4 audit; see linear.py)
        w_sum = jnp.maximum(maybe_psum(jnp.sum(w), axis_name), 1e-12)
        opt = optax.adam(self.lr)

        with jax.default_matmul_precision(self.precision):

            def local_data_loss(p):
                return jnp.sum(w * self.row_loss(p, X, y)) / w_sum

            def step(carry, _):
                p, opt_state = carry
                local, g = jax.value_and_grad(local_data_loss)(p)
                # penalty gradient by AD off penalty() itself, so the
                # optimized objective can never desync from the
                # reported one; added once, outside the psum
                g = jax.tree.map(
                    lambda a, b: maybe_psum(a, axis_name) + b,
                    g, jax.grad(self.penalty)(p),
                )
                loss = maybe_psum(local, axis_name) + self.penalty(p)
                updates, opt_state = opt.update(g, opt_state, p)
                return (optax.apply_updates(p, updates), opt_state), loss

            (p, _), losses = jax.lax.scan(
                step, (params, opt.init(params)), None,
                length=self.max_iter,
            )
            final = maybe_psum(
                jnp.sum(w * self.row_loss(p, X, y)), axis_name
            ) / w_sum + self.penalty(p)
        return p, {"loss": final, "loss_curve": losses}


class FMClassifier(_FMBase):
    """Multinomial factorization-machine classifier (softmax NLL over
    C FM score columns)."""

    task = "classification"

    def predict_scores(self, params, X):
        return self._raw_scores(params, X)

    def row_loss(self, params, X, y):
        logp = jax.nn.log_softmax(self._raw_scores(params, X), axis=-1)
        return -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]


class FMRegressor(_FMBase):
    """Factorization-machine regressor (squared loss)."""

    task = "regression"

    def predict_scores(self, params, X):
        return self._raw_scores(params, X)[:, 0]

    def row_loss(self, params, X, y):
        resid = self.predict_scores(params, X) - y.astype(jnp.float32)
        return 0.5 * resid * resid
