"""`vmap`-able depth-bounded decision trees — SURVEY §7 hard-part 1.

The reference plugs Spark ML DecisionTree (driver-orchestrated,
row-partitioned histogram split search on executors) into the bagging
loop [B:9, SURVEY §2a#2]. A literal port — per-node dynamic recursion —
cannot jit or `vmap`. The TPU-native design makes every shape static:

- **Dense complete binary tree** of static depth ``d``: node arrays of
  length ``2^d − 1`` (internal) and ``2^d`` (leaves). Growth is
  level-synchronous: every node at a level splits simultaneously, so a
  whole level's split search across all replicas is batched linear
  algebra, not control flow [SURVEY §7.7].
- **Quantile binning, shared across replicas.** ``prepare()`` computes
  per-feature quantile bin edges and a *cumulative* threshold-indicator
  matrix ``T[i, f, b] = (X[i, f] <= edge[f, b])`` once per ensemble
  (replica-invariant — the engine hoists it out of the replica map).
- **Split search = one matmul per level.** Left-of-threshold class/
  moment sums for every (feature, threshold, node) candidate are
  ``Tᵀ @ R`` with ``R[i, n·K + k] = onehot(node_i)[n] · S[i, k]`` —
  a dense ``(F·B, rows) × (rows, N·K)`` contraction that tiles onto
  the MXU, replacing the reference's executor-side histogram
  aggregation. Because T is cumulative in the bin axis, the product
  *is* the left-statistics table; no cumsum pass is needed.
- **Weighted everything**: the Poisson bootstrap counts enter as exact
  per-row weights in the split statistics and leaf values
  [SURVEY §7 hard-part 2].

Counts are accumulated in f32 on the MXU from ``hist_dtype`` operands;
``bfloat16`` operands are exact for the 0/1 indicator matrix and the
integer-valued bootstrap weights, so classification split counts are
exact. Regression moment sums (w·y, w·y²) round to bf16 per element —
split *selection* tolerates this; leaf values are computed separately
in full precision. Set ``hist_dtype="float32"`` to make split search
exact at 2× the memory.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

import numpy as np

from spark_bagging_tpu.models.base import BaseLearner
from spark_bagging_tpu.ops.precision import mosaic_dot_precision
from spark_bagging_tpu.ops.reduce import maybe_psum

_EPS = 1e-12


def _check_feature_subset(fs):
    """Validate a featureSubsetStrategy value; returns it unchanged."""
    if fs is None or fs in ("all", "sqrt", "log2", "onethird"):
        return fs
    if isinstance(fs, bool):
        raise ValueError(f"invalid feature_subset {fs!r}")
    if isinstance(fs, int):
        if fs < 1:
            raise ValueError(f"int feature_subset must be >= 1, got {fs}")
        return fs
    if isinstance(fs, float):
        if not 0.0 < fs <= 1.0:
            raise ValueError(
                f"float feature_subset must be in (0, 1], got {fs}"
            )
        return fs
    raise ValueError(
        "feature_subset must be None|'all'|'sqrt'|'log2'|'onethird'|"
        f"float|int, got {fs!r}"
    )


def _quantile_edges(X, row_mask, n_bins):
    """Per-feature interior bin edges ``(F, n_bins - 1)`` + valid count.

    Order-statistic quantiles over valid rows (``row_mask`` zeros mark
    padding added for even sharding — they are pushed to +inf before the
    sort so they never land in an interior bin). A shard with zero valid
    rows returns all-inf edges; callers must mask it out of cross-shard
    averaging (see :meth:`_TreeBase.prepare`).
    """
    n, F = X.shape
    Xt = X.T
    if row_mask is not None:
        Xt = jnp.where(row_mask[None, :] > 0, Xt, jnp.inf)
        n_valid = jnp.sum(row_mask > 0).astype(jnp.int32)
    else:
        n_valid = jnp.asarray(n, jnp.int32)
    Xs = jnp.sort(Xt, axis=1)  # (F, n)
    # b-th interior edge ≈ order statistic (b+1)/B · n_valid. Computed in
    # f32 (not `arange * n_valid // B`) so n_rows × n_bins can't overflow
    # int32 at Criteo scale; a ≤few-row rounding error in the position is
    # irrelevant to binning quality.
    pos = jnp.clip(
        (jnp.arange(1, n_bins, dtype=jnp.float32)
         * (n_valid.astype(jnp.float32) / n_bins)).astype(jnp.int32),
        0,
        n - 1,
    )
    return Xs[:, pos], n_valid  # (F, n_bins - 1)


def _psum_average_edges(interior, n_valid, axis_name):
    """Masked cross-shard averaging of quantile edges: shards holding
    at least one valid row contribute; padding-only shards (whose
    edges are +inf sentinels) are excluded. Shared by every learner
    that bins through ``_quantile_edges`` under a data mesh."""
    if axis_name is None:
        return interior
    has = (n_valid > 0).astype(interior.dtype)
    num = maybe_psum(
        jnp.where(jnp.isfinite(interior), interior, 0.0) * has,
        axis_name,
    )
    den = jnp.maximum(maybe_psum(has, axis_name), 1.0)
    return num / den


class _TreeBase(BaseLearner):
    """Shared growth engine for classifier/regressor trees.

    ``split_impl`` selects the split-search backend:

    - ``"dense"``: precompute the ``(n, F·B)`` indicator matrix T once
      per ensemble and contract ``Tᵀ @ R`` per level (XLA). Fastest
      when T fits HBM comfortably.
    - ``"fused"``: Pallas kernel (ops/hist.py) that builds indicator
      tiles on-chip per level — O(n·F) memory instead of O(n·F·B),
      the only feasible path at wide-feature scale [B:11].
    - ``"auto"`` (default): ``"fused"`` on TPU when T would exceed
      ~256 MB, else ``"dense"``.
    """

    # single trees stream through the multi-pass level-synchronous
    # engine (tree_stream.py); subclasses whose fitted params are NOT
    # one tree (boosting) must opt out or fit_stream would grow a
    # single tree and predict would read garbage
    tree_streamable = True

    def __init__(
        self,
        max_depth: int = 5,
        n_bins: int = 32,
        hist_dtype: str = "bfloat16",
        precision: str = "highest",
        split_impl: str = "auto",
        feature_subset: str | float | int | None = None,
        min_info_gain: float = 0.0,
        min_instances_per_node: float = 0.0,
    ):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        if n_bins < 2:
            raise ValueError(f"n_bins must be >= 2, got {n_bins}")
        if split_impl not in ("auto", "dense", "fused"):
            raise ValueError(
                f"split_impl must be auto|dense|fused, got {split_impl!r}"
            )
        _check_feature_subset(feature_subset)
        if min_info_gain < 0:
            raise ValueError(
                f"min_info_gain must be >= 0, got {min_info_gain}"
            )
        if min_instances_per_node < 0:
            raise ValueError(
                "min_instances_per_node must be >= 0, got "
                f"{min_instances_per_node}"
            )
        self.max_depth = max_depth
        self.n_bins = n_bins
        self.hist_dtype = hist_dtype
        self.precision = precision
        self.split_impl = split_impl
        self.feature_subset = feature_subset
        self.min_info_gain = min_info_gain
        self.min_instances_per_node = min_instances_per_node

    def _n_split_features(self, n_features: int) -> int | None:
        """Candidate features per SPLIT (Spark's featureSubsetStrategy
        [SURVEY §1 L3] / random-forest semantics): each node at each
        level considers a fresh random feature subset. None/'all' keeps
        every feature (plain decision tree)."""
        fs = _check_feature_subset(self.feature_subset)
        F = n_features
        if fs is None or fs == "all":
            return None
        if fs == "sqrt":
            k = int(np.ceil(np.sqrt(F)))
        elif fs == "log2":
            k = int(np.ceil(np.log2(max(F, 2))))
        elif fs == "onethird":
            k = int(np.ceil(F / 3))
        elif isinstance(fs, float):
            k = int(np.ceil(fs * F))
        else:  # int
            k = fs
        k = max(1, min(int(k), F))
        return None if k == F else k

    @staticmethod
    def _level_feat_mask(key, level, n_nodes, n_features, k):
        """(N, F) mask with exactly k candidate features per node,
        drawn from ``fold_in(key, level)`` — deterministic given the
        replica fit key, so streamed fits can replay it exactly."""
        rand = jax.random.uniform(
            jax.random.fold_in(key, level), (n_nodes, n_features)
        )
        kth = jnp.sort(rand, axis=1)[:, k - 1]
        return rand <= kth[:, None]

    def _resolved_impl(self, n_rows: int, n_features: int) -> str:
        if self.split_impl != "auto":
            return self.split_impl
        # Dense peak HBM per (row, feature, bin) element: the int8 T
        # indicator plus the hist_dtype Tf = T.reshape(...).astype(...)
        # copy materialized inside _grow — budget both, not just T.
        # NOTE: the fused kernel also has a VMEM feasibility envelope
        # (deepest-level output block (B·f_tile, N·K) f32);
        # ops/hist.py's guard raises a clear error with guidance when a
        # deep-tree/many-stat config exceeds it — set
        # split_impl="dense" there.
        bytes_per = 1 + jnp.dtype(self.hist_dtype).itemsize
        if (
            jax.default_backend() == "tpu"
            and n_rows * n_features * self.n_bins * bytes_per
            > 256 * 1024 * 1024
        ):
            return "fused"
        return "dense"

    # -- prepare hook ---------------------------------------------------

    def prepare(self, X, *, axis_name=None, row_mask=None):
        """Bin edges + cumulative threshold indicators (replica-invariant).

        Data-sharded fits compute per-shard quantiles and average them
        into one consistent global binning (any shard-agreed monotone
        edges are valid bins) [SURVEY §5 comms backend]. The average is
        masked over shards that hold at least one valid row, so a shard
        of pure padding (tiny n on a wide data axis) cannot poison the
        edges with its +inf sentinel values.
        """
        interior, n_valid = _quantile_edges(X, row_mask, self.n_bins)
        interior = _psum_average_edges(interior, n_valid, axis_name)
        F = X.shape[1]
        edges = jnp.concatenate(
            [interior, jnp.full((F, 1), jnp.inf, X.dtype)], axis=1
        )
        if self._resolved_impl(X.shape[0], F) == "fused":
            # the fused kernel builds indicator tiles on-chip — no T
            return {"edges": edges}
        T = (X[:, :, None] <= edges[None, :, :]).astype(jnp.int8)
        return {"edges": edges, "T": T}

    def gather_subspace(self, prepared, idx):
        out = {"edges": prepared["edges"][idx]}
        if "T" in prepared:
            out["T"] = prepared["T"][:, idx, :]
        return out

    def flops_per_fit(self, n_rows, n_features, n_outputs):
        # per level the split search is one (F·B, n) @ (n, N·K)
        # contraction (N = 2^level nodes, K = stats per row); summed
        # over levels N totals 2^d − 1. K: classes for classification,
        # 3 moments for regression.
        K = n_outputs if self.task == "classification" else 3
        nodes_total = 2**self.max_depth - 1
        return float(
            2 * n_rows * n_features * self.n_bins * K * nodes_total
        )

    def fit_workset_bytes(self, n_rows, n_features, n_outputs):
        # per-replica temps at the deepest level (N = 2^(d−1) nodes):
        # the (n, N·K) row-stat operand in hist_dtype; the (F, B, N, K)
        # f32 left-stats histogram PLUS its same-shape `right = total −
        # hist` copy in _select_splits; the (n, 2^d) f32 leaf one-hot
        # from _leaf_stats; weight/assignment vectors. The histogram
        # and one-hot were unmodeled and let auto_chunk_size admit
        # severalfold too many replicas at wide F [round-4 audit].
        K = n_outputs if self.task == "classification" else 3
        hist_bytes = 2 if self.hist_dtype == "bfloat16" else 4
        N = 2 ** (self.max_depth - 1)
        return float(
            hist_bytes * n_rows * N * K
            + 2 * 4.0 * n_features * self.n_bins * N * K
            + 4.0 * n_rows * (2 ** self.max_depth)
            + 8 * n_rows
        )

    def subspace_gather_bytes(self, n_rows, n_subspace, n_features=None):
        # under bagging subspaces the dense impl gathers a per-replica
        # T[:, idx, :] int8 slice plus its hist_dtype Tf copy in _grow
        # — ~(1 + hist_bytes)·B× the X gather alone [round-4 audit].
        # Whether T exists is prepare()'s decision at the FULL feature
        # width, so resolve the impl with n_features, not the subspace.
        base = 4.0 * n_rows * n_subspace
        width = n_features if n_features is not None else n_subspace
        if self._resolved_impl(n_rows, width) == "dense":
            hist_bytes = 2 if self.hist_dtype == "bfloat16" else 4
            base += (1 + hist_bytes) * n_rows * n_subspace * self.n_bins
        return base

    # -- growth ---------------------------------------------------------

    def _hdt(self):
        """Histogram matmul dtype; CPU XLA lacks BF16×BF16→F32 dots, so
        the fake-device test backend [SURVEY §4] upgrades to f32."""
        hdt = jnp.dtype(self.hist_dtype)
        if hdt == jnp.bfloat16 and jax.default_backend() == "cpu":
            hdt = jnp.dtype(jnp.float32)
        return hdt

    def _select_splits(self, hist, edges, feat_mask=None):
        """One level's split choice from its left-stats table.

        ``hist``: ``(F, B, N, K)`` cumulative left statistics. Returns
        ``(feature, threshold, score_sum)`` for the level's N nodes —
        shared by the in-memory growth loop and the streaming fit.
        ``feat_mask`` (N, F) restricts each node's candidate features
        (random-forest per-split sampling); masked-out candidates score
        +inf so the argmin never picks them.

        Spark's pre-pruning regularizers [SURVEY §1 L3 param parity]
        live here so the streamed fit inherits them: candidates whose
        left or right side holds fewer than ``min_instances_per_node``
        WEIGHTED rows score +inf (with integer Poisson bootstrap
        weights that is an instance count in Spark's sense; with
        fractional user sample_weight it is weight mass — scale the
        threshold accordingly, which is why the gate defaults OFF at
        0.0), and a node whose best decrease falls under
        ``min_info_gain`` (or with no valid candidate at all) becomes
        a leaf — its threshold is +inf, which routes every row left,
        leaving the right subtree empty.
        """
        B = self.n_bins
        N = hist.shape[2]
        total = hist[0, -1]  # edge B-1 is +inf ⇒ full-node sums
        right = total[None, None, :, :] - hist
        score = self._impurity(hist) + self._impurity(right)
        if feat_mask is not None:
            score = jnp.where(
                feat_mask.T[:, None, :], score, jnp.inf
            )
        if self.min_instances_per_node > 0:
            ok = (
                (self._row_count(hist) >= self.min_instances_per_node)
                & (self._row_count(right) >= self.min_instances_per_node)
            )
            score = jnp.where(ok, score, jnp.inf)
        best = jnp.argmin(score.reshape(-1, N), axis=0)
        bf = (best // B).astype(jnp.int32)
        bb = (best % B).astype(jnp.int32)
        thr = edges[bf, bb]
        child = jnp.take_along_axis(
            score.reshape(-1, N), best[None, :], axis=0
        )[0]
        # per-node impurity decrease — the MDI numerator for
        # ``feature_importances_`` (Spark ML featureImportances analog)
        gain = jnp.maximum(self._impurity(total) - child, 0.0)
        # leaf-ification: no valid candidate, or decrease under the
        # floor — keep the node whole (leaf stats absorb its rows)
        keep = jnp.isfinite(child) & (gain >= self.min_info_gain)
        thr = jnp.where(keep, thr, jnp.inf)
        gain = jnp.where(keep, gain, 0.0)
        child = jnp.where(keep, child, self._impurity(total))
        return bf, thr, jnp.sum(child), gain

    def _row_count(self, stats):
        """Weighted row mass per candidate side (pre-pruning counts);
        stats ``(..., K)``. Regression stats carry it in moment 0."""
        return stats[..., 0]

    def _chunk_level_hist(self, Xs, S, edges, node, N):
        """Left-stats table ``(F, B, N, K)`` for one row block, with the
        threshold indicator built on the fly — the streaming fit's
        per-chunk accumulation step (memory O(chunk·F·B), independent
        of total rows) [SURVEY §7 hard-part 4]."""
        n, F = Xs.shape
        B = self.n_bins
        K = S.shape[1]
        hdt = self._hdt()
        if self._resolved_impl(n, F) == "fused":
            from spark_bagging_tpu.ops.hist import binned_left_stats

            return binned_left_stats(
                Xs, edges, node, S, n_nodes=N, hist_dtype=str(hdt),
                interpret=jax.default_backend() != "tpu",
            )
        Tf = (
            (Xs[:, :, None] <= edges[None, :, :])
            .reshape(n, F * B)
            .astype(hdt)
        )
        R = (
            jax.nn.one_hot(node, N, dtype=hdt)[:, :, None]
            * S.astype(hdt)[:, None, :]
        ).reshape(n, N * K)
        # Same dot-precision rule as the fused kernel (ops/precision
        # .py): with hist_dtype=float32 the kernel pins an exact-f32
        # contract, and a size-dependent split_impl="auto" choice must
        # not change numerics — so the dense matmul pins it too
        # instead of inheriting the ambient precision context.
        return jnp.matmul(
            Tf.T, R, preferred_element_type=jnp.float32,
            precision=mosaic_dot_precision(hdt),
        ).reshape(F, B, N, K)

    def _grow(self, X, S, prepared, axis_name, key=None):
        """Level-synchronous growth; returns (feature, threshold,
        per-node gain, leaf_index_per_row, per-level impurity curve).

        ``S`` is the per-row statistics matrix ``(n, K)`` whose left/
        right sums drive the impurity: weighted one-hot classes for
        classification, weighted moments ``(w, w·y, w·y²)`` for
        regression. ``key`` (the replica fit key) seeds the per-split
        feature masks when ``feature_subset`` is set.
        """
        n, F = X.shape
        B, d = self.n_bins, self.max_depth
        K = S.shape[1]
        k_split = self._n_split_features(F)
        if k_split is not None and key is None:
            raise ValueError(
                "feature_subset per-split sampling needs the replica "
                "fit key; call fit() rather than _grow() directly"
            )
        edges = prepared["edges"]
        fused = "T" not in prepared
        hdt = self._hdt()
        if not fused:
            Tf = prepared["T"].reshape(n, F * B).astype(hdt)
        Sh = S.astype(hdt)

        node = jnp.zeros((n,), jnp.int32)  # level-relative node index
        feats, thrs, curve, gains = [], [], [], []
        with jax.default_matmul_precision(self.precision):
            for level in range(d):
                N = 2**level
                if fused:
                    from spark_bagging_tpu.ops.hist import (
                        binned_left_stats,
                    )

                    hist = maybe_psum(
                        binned_left_stats(
                            X, edges, node, S,
                            n_nodes=N,
                            hist_dtype=str(hdt),
                            interpret=jax.default_backend() != "tpu",
                        ),
                        axis_name,
                    )
                else:
                    R = (
                        jax.nn.one_hot(node, N, dtype=hdt)[:, :, None]
                        * Sh[:, None, :]
                    ).reshape(n, N * K)
                    # (F·B, N·K) left statistics — the level's whole
                    # split search as one MXU contraction (f32 accum);
                    # precision pinned to match the fused kernel so
                    # impl choice never changes numerics.
                    hist = maybe_psum(
                        jnp.matmul(
                            Tf.T, R,
                            preferred_element_type=jnp.float32,
                            precision=mosaic_dot_precision(hdt),
                        ),
                        axis_name,
                    ).reshape(F, B, N, K)
                mask = (
                    self._level_feat_mask(key, level, N, F, k_split)
                    if k_split is not None else None
                )
                bf, thr, score_sum, gain = self._select_splits(
                    hist, edges, mask
                )
                feats.append(bf)
                thrs.append(thr)
                curve.append(score_sum)
                gains.append(gain)
                f_row = bf[node]
                t_row = thr[node]
                x_sel = jnp.take_along_axis(X, f_row[:, None], axis=1)[:, 0]
                node = node * 2 + (x_sel > t_row).astype(jnp.int32)
        return (
            jnp.concatenate(feats),
            jnp.concatenate(thrs),
            jnp.concatenate(gains),
            node,
            jnp.stack(curve),
        )

    def _leaf_stats(self, node, S, axis_name):
        """Per-leaf statistic sums ``(2^d, K)`` in full precision."""
        L = 2**self.max_depth
        with jax.default_matmul_precision("highest"):
            onehot = jax.nn.one_hot(node, L, dtype=jnp.float32)
            return maybe_psum(
                jnp.matmul(
                    onehot.T,
                    S.astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                ),
                axis_name,
            )

    # -- routing (shared by fit-time and predict-time) ------------------

    def _leaf_str(self, params, leaf_idx: int) -> str:
        raise NotImplementedError

    def to_debug_string(self, params, feature_names=None) -> str:
        """Human-readable tree dump — Spark's
        ``DecisionTree*Model.toDebugString`` analog, decoded from the
        static level-ordered node arrays. Non-finite thresholds are the
        engine's pre-pruned / unsplit markers (every row routes left),
        rendered as the leaf they effectively are. For a bagged
        ensemble, dump replica ``i`` via::

            clf.base_learner_.to_debug_string(clf.replica_params(i)[0])
        """
        feat = np.asarray(params["feature"])
        thr = np.asarray(params["threshold"])

        def name(f):
            return (
                feature_names[f] if feature_names is not None
                else f"feature {f}"
            )

        lines: list[str] = []
        n_splits = 0  # REACHABLE splits only: empty nodes inside an
        # unsplit ancestor's dead subtree keep finite thresholds
        # (gain 0 passes min_info_gain=0), so a flat isfinite count
        # would overstate what the dump renders [round-4 audit]

        def walk(level: int, rel: int, indent: int) -> None:
            nonlocal n_splits
            pad = " " * indent
            if level == self.max_depth:
                lines.append(pad + self._leaf_str(params, rel))
                return
            node = (2**level - 1) + rel
            if not np.isfinite(thr[node]):
                # unsplit/pre-pruned: all rows route left — render the
                # reachable subtree without the phantom split
                walk(level + 1, 2 * rel, indent)
                return
            n_splits += 1
            lines.append(
                pad + f"If ({name(int(feat[node]))} <= {thr[node]:.6g})"
            )
            walk(level + 1, 2 * rel, indent + 1)
            lines.append(
                pad + f"Else ({name(int(feat[node]))} > {thr[node]:.6g})"
            )
            walk(level + 1, 2 * rel + 1, indent + 1)

        walk(0, 0, 1)
        header = (
            f"{type(self).__name__} (depth={self.max_depth}, "
            f"splits={n_splits})"
        )
        return "\n".join([header] + lines)

    def _route(self, params, X):
        """Leaf index per row via ``max_depth`` gather-compare steps."""
        rel = jnp.zeros((X.shape[0],), jnp.int32)
        off = 0
        for level in range(self.max_depth):
            N = 2**level
            f_lvl = params["feature"][off : off + N]
            t_lvl = params["threshold"][off : off + N]
            f_row = f_lvl[rel]
            t_row = t_lvl[rel]
            x_sel = jnp.take_along_axis(X, f_row[:, None], axis=1)[:, 0]
            rel = rel * 2 + (x_sel > t_row).astype(jnp.int32)
            off += N
        return rel

    def _impurity(self, stats):
        raise NotImplementedError


class DecisionTreeClassifier(_TreeBase):
    """Weighted-Gini, depth-``d`` classification tree (config 3 [B:9]).

    Leaves store Laplace-smoothed log class probabilities, so
    ``predict_scores`` feeds soft voting as ``softmax(logp) = p`` and
    hard voting as the leaf's majority class.
    """

    task = "classification"

    def __init__(
        self,
        max_depth: int = 5,
        n_bins: int = 32,
        leaf_smoothing: float = 1.0,
        hist_dtype: str = "bfloat16",
        precision: str = "highest",
        split_impl: str = "auto",
        feature_subset: str | float | int | None = None,
        min_info_gain: float = 0.0,
        min_instances_per_node: float = 0.0,
        criterion: str = "gini",
    ):
        super().__init__(
            max_depth, n_bins, hist_dtype, precision, split_impl,
            feature_subset, min_info_gain, min_instances_per_node,
        )
        if criterion not in ("gini", "entropy"):
            raise ValueError(
                f"criterion must be gini|entropy, got {criterion!r}"
            )
        if leaf_smoothing < 0:
            raise ValueError(
                f"leaf_smoothing must be >= 0, got {leaf_smoothing}"
            )
        self.leaf_smoothing = leaf_smoothing
        self.criterion = criterion

    def init_params(self, key, n_features, n_outputs):
        del key
        M, L = 2**self.max_depth - 1, 2**self.max_depth
        return {
            "feature": jnp.zeros((M,), jnp.int32),
            "threshold": jnp.zeros((M,), jnp.float32),
            "gain": jnp.zeros((M,), jnp.float32),
            "leaf_logp": jnp.zeros((L, n_outputs), jnp.float32),
        }

    def _impurity(self, stats):
        """Weighted impurity mass per (feature, bin, node) side; stats
        is class counts ``(F, B, N, C)``. Gini: ``|side|·(1 − Σp²)``.
        Entropy (Spark's other impurity): ``|side|·H = −Σ c·log(c/w)``
        in nats."""
        w = stats.sum(-1)
        if self.criterion == "entropy":
            frac = stats / jnp.maximum(w, _EPS)[..., None]
            return -jnp.sum(
                stats * jnp.log(jnp.maximum(frac, _EPS)), axis=-1
            )
        return w - (stats**2).sum(-1) / jnp.maximum(w, _EPS)

    def _row_count(self, stats):
        return stats.sum(-1)

    def _row_stats(self, y, w, n_outputs):
        """Per-row split statistics: weighted one-hot class counts."""
        return w[:, None] * jax.nn.one_hot(y, n_outputs, dtype=jnp.float32)

    def _finalize_leaves(self, feature, threshold, gain, counts, curve):
        """Leaf log-probabilities + report from leaf class counts —
        shared by the in-memory fit and the streaming fit."""
        C = counts.shape[1]
        a = self.leaf_smoothing
        totals = counts.sum(-1, keepdims=True)
        # empty leaves (a pure split upstream leaves whole subtrees
        # unpopulated) fall back to uniform log-probs — without this,
        # leaf_smoothing=0 yields log(0/0)=NaN leaves that silently
        # poison predictions for any row routed there (the regressor's
        # global-mean fallback, classifier-shaped)
        logp = jnp.where(
            totals > 0,
            jnp.log((counts + a) / jnp.maximum(totals + a * C, _EPS)),
            jnp.log(1.0 / C),
        )
        w_tot = jnp.maximum(counts.sum(), _EPS)
        leaf_gini = jnp.sum(self._impurity(counts))
        new = {
            "feature": feature,
            "threshold": threshold,
            "gain": gain.astype(jnp.float32),
            "leaf_logp": logp.astype(jnp.float32),
        }
        return new, {
            "loss": leaf_gini / w_tot,
            "loss_curve": curve / w_tot,
        }

    def fit(self, params, X, y, sample_weight, key, *, axis_name=None,
            prepared=None):
        if prepared is None:
            prepared = self.prepare(X, axis_name=axis_name)
        C = params["leaf_logp"].shape[1]
        S = self._row_stats(y, sample_weight.astype(jnp.float32), C)
        feature, threshold, gain, node, curve = self._grow(
            X, S, prepared, axis_name, key
        )
        counts = self._leaf_stats(node, S, axis_name)  # (L, C)
        return self._finalize_leaves(feature, threshold, gain, counts, curve)

    def predict_scores(self, params, X):
        return params["leaf_logp"][self._route(params, X)]

    def _leaf_str(self, params, leaf_idx):
        logp = np.asarray(params["leaf_logp"][leaf_idx])
        c = int(logp.argmax())
        return f"Predict: {c} (p={float(np.exp(logp[c])):.3f})"


class DecisionTreeRegressor(_TreeBase):
    """Weighted-variance (SSE) regression tree.

    Leaves store the weighted mean target; empty leaves fall back to
    the global weighted mean (only out-of-bag rows can reach them).
    """

    task = "regression"

    def init_params(self, key, n_features, n_outputs):
        del key, n_outputs
        M, L = 2**self.max_depth - 1, 2**self.max_depth
        return {
            "feature": jnp.zeros((M,), jnp.int32),
            "threshold": jnp.zeros((M,), jnp.float32),
            "gain": jnp.zeros((M,), jnp.float32),
            "leaf_value": jnp.zeros((L,), jnp.float32),
        }

    def _impurity(self, stats):
        """Weighted SSE ``Σw·y² − (Σw·y)²/Σw`` per candidate side;
        stats is moment sums ``(F, B, N, 3)`` of (w, w·y, w·y²)."""
        s0, s1, s2 = stats[..., 0], stats[..., 1], stats[..., 2]
        return s2 - s1**2 / jnp.maximum(s0, _EPS)

    def _row_stats(self, y, w, n_outputs):
        """Per-row split statistics: weighted moments (w, w·y, w·y²)."""
        del n_outputs
        yf = y.astype(jnp.float32)
        return jnp.stack([w, w * yf, w * yf**2], axis=1)

    def _finalize_leaves(self, feature, threshold, gain, m, curve):
        """Leaf means + report from leaf moment sums ``(L, 3)`` —
        shared by the in-memory fit and the streaming fit."""
        w_tot = jnp.maximum(m[:, 0].sum(), _EPS)
        global_mean = m[:, 1].sum() / w_tot
        value = jnp.where(
            m[:, 0] > 0, m[:, 1] / jnp.maximum(m[:, 0], _EPS), global_mean
        )
        sse = jnp.sum(self._impurity(m))
        new = {
            "feature": feature,
            "threshold": threshold,
            "gain": gain.astype(jnp.float32),
            "leaf_value": value.astype(jnp.float32),
        }
        return new, {"loss": sse / w_tot, "loss_curve": curve / w_tot}

    def fit(self, params, X, y, sample_weight, key, *, axis_name=None,
            prepared=None):
        del params
        if prepared is None:
            prepared = self.prepare(X, axis_name=axis_name)
        S = self._row_stats(y, sample_weight.astype(jnp.float32), 1)
        feature, threshold, gain, node, curve = self._grow(
            X, S, prepared, axis_name, key
        )
        m = self._leaf_stats(node, S, axis_name)  # (L, 3)
        return self._finalize_leaves(feature, threshold, gain, m, curve)

    def predict_scores(self, params, X):
        return params["leaf_value"][self._route(params, X)]

    def _leaf_str(self, params, leaf_idx):
        return f"Predict: {float(params['leaf_value'][leaf_idx]):.6g}"
