"""The base-learner plugin contract — the TPU-native `BaggingParams` slot.

The reference's plugin point is the Spark `Estimator`/`Model` contract:
any Predictor can be set as the base learner [B:5]. The TPU-native
contract replaces object-oriented fit/transform with three pure
functions, each `vmap`-able over a leading replica axis [SURVEY §7.3]:

- ``init_params(key, n_features, n_outputs) -> params``
- ``fit(params, X, y, sample_weight, key, axis_name) -> (params, aux)``
  (learners declaring a ``prepare`` hook additionally receive their
  precomputed state via a ``prepared=`` keyword — see below)
- ``predict_scores(params, X) -> scores``

Rules that make a learner a valid plugin:

- **Weighted fit.** ``sample_weight`` carries the Poisson bootstrap
  counts; the learner must treat them as exact per-row multiplicities or
  accuracy parity fails silently [SURVEY §7 hard-part 2].
- **Static shapes, no data-dependent Python control flow** — the fit is
  traced once and compiled; iteration counts are hyperparameters.
- **Row reductions go through ``maybe_psum(_, axis_name)``** so the same
  code runs single-device or data-parallel under ``shard_map`` with rows
  sharded over a mesh axis [SURVEY §5 comms backend].
- Hyperparameters live on the (hashable, static) learner object; traced
  state lives in ``params`` (a pytree).

``scores`` are logits ``(n, n_classes)`` for classification and values
``(n,)`` for regression.
"""

from __future__ import annotations

from typing import Any, ClassVar

import jax
import jax.numpy as jnp

from spark_bagging_tpu.utils.params import ParamsMixin

Params = Any  # a pytree of arrays
Aux = dict[str, jax.Array]


def augment_bias(X: jax.Array) -> jax.Array:
    """Append a bias column of ones — the shared convention for linear
    learners: weights are ``(d+1, C)`` with the bias in the LAST row,
    which ``W[:-1]``-style penalties throughout depend on."""
    return jnp.concatenate([X, jnp.ones((X.shape[0], 1), X.dtype)], axis=1)


class BaseLearner(ParamsMixin):
    """Abstract base-learner contract (see module docstring)."""

    task: ClassVar[str]  # "classification" | "regression"
    # Streamable learners additionally implement ``row_loss``/``penalty``
    # so the out-of-core engine (streaming.py) can take minibatch
    # gradients over data chunks. Closed-form / structure-search
    # learners (trees) are not streamable [SURVEY §7 step 8].
    streamable: ClassVar[bool] = False
    # Learners that consume a per-row auxiliary column (e.g. the AFT
    # censor indicator — Spark's censorCol) declare ``uses_aux = True``
    # and accept an ``aux=`` keyword in ``fit``. The ensemble engine
    # threads the column through bootstrap/vmap/mesh sharding alongside
    # ``y``; learners without the flag never see the kwarg (the
    # ``prepared`` pattern), so the plain contract is unchanged
    # [VERDICT r2 ask#7].
    uses_aux: ClassVar[bool] = False
    # Learners that can warm-start every replica from ONE shared
    # ensemble-level solve (e.g. logistic regression's pooled unweighted
    # optimum — the problem is convex, so per-replica refinement from a
    # good shared start reaches the same optimum in far fewer
    # iterations) expose ``uses_pooled_init`` (typically a property on
    # an ``init="pooled"`` hyperparam) and implement ``pooled_init``.
    # The engine calls ``pooled_init`` once outside the replica map and
    # threads the result through ``prepared``/``gather_subspace`` into
    # ``initial_params`` — the same plumbing as ``prepare``.
    uses_pooled_init: ClassVar[bool] = False

    def pooled_amortizes(self, n_replicas: int) -> bool:
        """Is the pooled pre-pass worth running for an ensemble of this
        TOTAL size? The engine consults this before paying the shared
        solve; the default says yes (learners with a cost model
        override — PooledStartMixin)."""
        del n_replicas
        return True

    def init_params(
        self, key: jax.Array, n_features: int, n_outputs: int
    ) -> Params:
        raise NotImplementedError

    def pooled_init(
        self,
        key: jax.Array,
        prepared: Any,
        X: jax.Array,
        y: jax.Array,
        n_outputs: int,
        *,
        row_mask: jax.Array | None = None,
        axis_name: str | None = None,
    ) -> Any:
        """Shared warm-start state, computed once per ensemble; returned
        value replaces ``prepared`` for this fit."""
        raise NotImplementedError

    def initial_params(
        self, key: jax.Array, n_features: int, n_outputs: int,
        prepared: Any | None,
    ) -> Params:
        """Per-replica initial params; sees the prepared state so a
        pooled warm start can override the cold ``init_params``."""
        del prepared
        return self.init_params(key, n_features, n_outputs)


    def fit(
        self,
        params: Params,
        X: jax.Array,
        y: jax.Array,
        sample_weight: jax.Array,
        key: jax.Array,
        *,
        axis_name: str | None = None,
        prepared: Any | None = None,
    ) -> tuple[Params, Aux]:
        raise NotImplementedError

    def predict_scores(self, params: Params, X: jax.Array) -> jax.Array:
        raise NotImplementedError

    # -- optional streaming contract ------------------------------------
    #
    # ``row_loss(params, X, y) -> (n,)`` per-row unweighted loss and
    # ``penalty(params) -> scalar`` let the out-of-core engine fit the
    # learner by stochastic gradient over data chunks with per-chunk
    # Poisson weights [P:5]. Only meaningful when ``streamable = True``.

    def row_loss(
        self, params: Params, X: jax.Array, y: jax.Array
    ) -> jax.Array:
        raise NotImplementedError(
            f"{type(self).__name__} does not support streaming fits"
        )

    def penalty(self, params: Params) -> jax.Array:
        raise NotImplementedError(
            f"{type(self).__name__} does not support streaming fits"
        )

    def sgd_step_flops(
        self, chunk_rows: int, n_features: int, n_outputs: int
    ) -> float | None:
        """Matmul FLOPs for ONE streamed optimizer step (fwd + bwd) on
        one chunk for one replica; None = unmodeled (the stream report
        then omits MFU rather than inventing it).

        Accounting rule, consistent with ``flops_per_fit``: backward ≈
        2× forward (each forward matmul induces two adjoint matmuls),
        so implementations return 3 × forward-matmul FLOPs on the FULL
        padded chunk — padded rows run through the MXU too, so they
        count toward achieved device FLOPs. Elementwise work (losses,
        masks, Adam updates) is excluded: matmul-only accounting
        [VERDICT r2 weak#5 → r3 ask#6].
        """
        del chunk_rows, n_features, n_outputs
        return None

    # -- optional replica-invariant precomputation ----------------------
    #
    # Some learners (trees) need work that depends only on X — quantile
    # bin edges, threshold-indicator matrices. Computing it inside `fit`
    # would repeat it per replica chunk; the ensemble engine instead
    # calls `prepare` ONCE outside the replica map and threads the
    # result into every `fit` via the `prepared` kwarg. When replicas
    # draw feature subspaces, `gather_subspace` slices the prepared
    # state to replica k's columns (runs inside the vmap).

    def prepare(
        self,
        X: jax.Array,
        *,
        axis_name: str | None = None,
        row_mask: jax.Array | None = None,
    ) -> Any | None:
        """Replica-invariant precomputation; None means 'nothing'."""
        del X, axis_name, row_mask
        return None

    def gather_subspace(self, prepared: Any, idx: jax.Array) -> Any:
        """Restrict prepared state to the feature columns in ``idx``."""
        return prepared

    # -- optional analytic cost model -----------------------------------

    def flops_per_fit(
        self, n_rows: int, n_features: int, n_outputs: int
    ) -> float | None:
        """Analytic floating-point ops for ONE base-learner fit.

        Used by ``fit_report`` to derive achieved TFLOP/s and MFU so
        performance is judged against the chip, not only a CPU proxy
        [VERDICT r1]. Counts f32-equivalent multiply+add as 2 ops.
        None means "no cost model" (the report omits MFU).
        """
        del n_rows, n_features, n_outputs
        return None

    def fit_workset_bytes(
        self, n_rows: int, n_features: int, n_outputs: int
    ) -> float | None:
        """Approximate peak per-replica device bytes for one fit —
        the dominant temporaries only (weights vector, solver temps),
        NOT the shared X (broadcast once per device). Drives automatic
        ``chunk_size`` resolution (utils/memory.py [VERDICT r2 ask#8]);
        None = unmodeled, callers keep the legacy vmap-all behavior.
        """
        del n_rows, n_features, n_outputs
        return None

    def subspace_gather_bytes(
        self, n_rows: int, n_subspace: int, n_features: int | None = None
    ) -> float:
        """Per-replica bytes of the feature-subspace gather built
        inside the replica vmap — the ``X[:, idx]`` f32 copy by
        default. Learners whose ``prepare()`` product is ALSO gathered
        per replica (trees' ``T`` indicator slice) override with the
        larger figure [round-4 audit]; such overrides get the FULL
        ``n_features`` because ``prepare()`` decides what exists at
        full width. Added to ``fit_workset_bytes`` by
        ``utils.memory.auto_chunk_size`` whenever the gather is active;
        not part of the workset model itself."""
        del n_features
        return 4.0 * n_rows * n_subspace

    # -- convenience used by the ensemble engine ------------------------

    def fit_from_init(
        self,
        key: jax.Array,
        X: jax.Array,
        y: jax.Array,
        sample_weight: jax.Array,
        n_outputs: int,
        *,
        axis_name: str | None = None,
        prepared: Any | None = None,
        aux: jax.Array | None = None,
    ) -> tuple[Params, Aux]:
        """Init-then-fit with a split key; one replica's whole training."""
        from spark_bagging_tpu.ops.bootstrap import split_init_fit

        init_key, fit_key = split_init_fit(key)
        params = self.initial_params(init_key, X.shape[1], n_outputs, prepared)
        kwargs = {}
        if prepared is not None:
            # Only learners with a prepare() hook receive the kwarg, so
            # third-party learners written to the plain fit contract
            # (no `prepared` parameter) keep working.
            kwargs["prepared"] = prepared
        if self.uses_aux:
            kwargs["aux"] = aux
        return self.fit(
            params, X, y, sample_weight, fit_key,
            axis_name=axis_name, **kwargs,
        )

    # Learners are static (hashable) w.r.t. jit: two instances with equal
    # hyperparams trace to the same compiled program.
    def _params_key(self) -> tuple:
        return tuple(
            sorted((k, repr(v))
                   for k, v in self.get_params(deep=False).items())
        )

    def __hash__(self) -> int:
        return hash((type(self),) + self._params_key())

    def __eq__(self, other: object) -> bool:
        # repr-based on BOTH sides: __eq__ via == with a repr-based
        # __hash__ broke the hash invariant (max_iter=1 vs 1.0 compared
        # equal but hashed apart), silently duplicating compiled
        # executables in bagging.py's lru caches [round-4 audit]
        return (
            type(self) is type(other)
            and self._params_key() == other._params_key()  # type: ignore[union-attr]
        )


class PooledStartMixin:
    """Pooled warm start for CONVEX learners (logistic/GLM/SVC):
    ``init="pooled"`` solves the unweighted pooled problem once per
    ensemble (``pooled_iter`` solver steps, amortized over all
    replicas) and starts every replica's weighted fit from that shared
    solution. Convexity is load-bearing — each replica's objective has
    a unique optimum, so the init changes the solver's path, not its
    destination; for non-convex learners (MLP, FM) a shared start would
    instead collapse ensemble diversity, so they must NOT use this.

    This amortization is an ensemble-LEVEL optimization the reference's
    per-fit driver loop cannot express [SURVEY §3.1]: Spark fits each
    replica as an independent job, while here the pooled solve is one
    more node in the single XLA program.

    Subclass requirements: list this mixin BEFORE ``BaseLearner`` in
    the bases, declare ``init``/``pooled_iter`` hyperparams (validated
    in ``__init__``), keep coefficients in a single params leaf named
    ``_pooled_leaf`` with the bias row/element LAST, and a ``fit`` that
    honors arbitrary initial params AND accepts (it may ignore) a
    ``prepared=`` keyword — with pooled init active the engine's
    ``prepared`` state is non-None, so ``fit_from_init`` forwards it.
    """

    _pooled_leaf: ClassVar[str] = "W"

    @property
    def uses_pooled_init(self) -> bool:
        return self.init == "pooled"

    def pooled_amortizes(self, n_replicas: int) -> bool:
        """Small-bag gate [ADVICE r5 low]: the pre-pass costs
        ``pooled_iter`` full-data solver iterations on top of unchanged
        per-replica work; the measured benefit is ~2 saved iterations
        per replica (one warm refinement iteration ≈ three cold ones,
        tests/test_pooled_init.py). It pays once ``2·R ≥ pooled_iter``
        — at the default ``pooled_iter=5``, bags of 1-2 replicas skip
        the solve and start from the cold init instead."""
        return 2 * n_replicas >= self.pooled_iter

    def pooled_init(self, key, prepared, X, y, n_outputs, *,
                    row_mask=None, axis_name=None):
        del prepared  # these learners have no other prepared state
        w = (jnp.ones(X.shape[0], jnp.float32) if row_mask is None
             else row_mask.astype(jnp.float32))
        solver = type(self)(**{
            **self.get_params(), "init": "zeros",
            "max_iter": self.pooled_iter,
        })
        params0 = solver.init_params(key, X.shape[1], n_outputs)
        params, _ = solver.fit(params0, X, y, w, key, axis_name=axis_name)
        return params[self._pooled_leaf]

    def gather_subspace(self, prepared, idx):
        if prepared is None:
            return None
        # restrict the pooled solution to this replica's feature
        # subspace; the bias (last row/element) rides along
        return jnp.concatenate([prepared[idx], prepared[-1:]], axis=0)

    def initial_params(self, key, n_features, n_outputs, prepared):
        if self.init == "pooled" and prepared is not None:
            return {self._pooled_leaf: prepared}
        return self.init_params(key, n_features, n_outputs)

    @staticmethod
    def validate_init(init: str) -> str:
        if init not in ("zeros", "pooled"):
            raise ValueError(f"init must be zeros|pooled, got {init!r}")
        return init
