"""Weighted Gaussian naive Bayes — a closed-form base learner.

The reference accepts any Spark ML Predictor as the base learner
(NaiveBayes among them) [B:5, SURVEY §1 L3]; this is the TPU-native
counterpart for the continuous-feature case. The whole fit is three
weighted moment reductions over rows — one fused pass of
``(C, n) @ (n, F)`` matmuls on the MXU, trivially ``vmap``-able over
replicas and exactly data-parallel through ``maybe_psum``
[SURVEY §7 hard-part 2].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_bagging_tpu.models.base import Aux, BaseLearner, Params
from spark_bagging_tpu.ops.reduce import maybe_psum

_LOG_2PI = 1.8378770664093453


class GaussianNB(BaseLearner):
    """Gaussian naive Bayes with sample-weight support.

    ``var_smoothing`` adds a fraction of the largest feature variance to
    every variance (sklearn's convention), keeping log-likelihoods
    finite on constant features and under tiny bootstrap samples.
    """

    task = "classification"
    streamable = False  # closed-form; one pass, no gradient stream

    def __init__(self, var_smoothing: float = 1e-9):
        self.var_smoothing = var_smoothing

    def init_params(self, key, n_features, n_outputs):
        del key
        return {
            "log_prior": jnp.zeros((n_outputs,), jnp.float32),
            # means are stored relative to a global shift (the weighted
            # feature means) so both fit and predict moments stay O(std)
            # — see the cancellation notes in fit/predict_scores
            "shift": jnp.zeros((n_features,), jnp.float32),
            "mean": jnp.zeros((n_outputs, n_features), jnp.float32),
            "var": jnp.ones((n_outputs, n_features), jnp.float32),
        }

    def flops_per_fit(self, n_rows, n_features, n_outputs):
        # two (C, n)@(n, F) moment matmuls + the weighted row sums
        return float(4 * n_rows * n_features * n_outputs
                     + 4 * n_rows * n_outputs)

    def fit(self, params, X, y, sample_weight, key, *,
            axis_name=None, prepared=None) -> tuple[Params, Aux]:
        del key, prepared
        C = params["mean"].shape[0]
        X = X.astype(jnp.float32)
        w = sample_weight.astype(jnp.float32)
        # (C, n) class-weighted row selector: Yw[c, i] = w_i·[y_i = c]
        Yw = jax.nn.one_hot(y, C, dtype=jnp.float32).T * w[None, :]
        cls_w = maybe_psum(Yw.sum(axis=1), axis_name)          # (C,)
        w_sum = jnp.maximum(cls_w.sum(), 1e-12)
        denom = jnp.maximum(cls_w, 1e-12)[:, None]
        # Shifted moments: raw E[x²] − μ² catastrophically cancels in
        # f32 when |mean| ≫ std (timestamp-like features); centering on
        # the global weighted mean first keeps the subtraction small.
        gmean = maybe_psum(w @ X, axis_name) / w_sum           # (F,)
        Xs = X - gmean[None, :]
        s1 = maybe_psum(Yw @ Xs, axis_name)                    # (C, F)
        s2 = maybe_psum(Yw @ (Xs * Xs), axis_name)             # (C, F)
        dmean = s1 / denom                                     # μ_c − g
        var = jnp.maximum(s2 / denom - dmean**2, 0.0)
        # sklearn-style smoothing: epsilon ∝ max feature variance of
        # the weighted data. One-hot rows partition the weights, so the
        # global second moment is just Σ_c s2 — no extra reduction.
        gvar = jnp.maximum(s2.sum(axis=0) / w_sum, 0.0)
        # floored smoothing: with every selected feature constant
        # (or an all-zero draw) max(gvar) is exactly 0 and the
        # smoothing term would vanish, making 1/var inf and the
        # scores NaN — the finiteness the docstring promises
        # [round-4 audit]
        var = var + jnp.maximum(
            self.var_smoothing * jnp.max(gvar), 1e-12
        )
        log_prior = jnp.log(jnp.maximum(cls_w, 1e-12) / w_sum)
        params = {
            "log_prior": log_prior, "shift": gmean, "mean": dmean,
            "var": var,
        }
        # weighted mean NLL, for the loss curve/report (the shared
        # helper — one NLL definition per module)
        loss = _weighted_nll(self, params, X, y, w, w_sum, axis_name)
        return params, {"loss": loss, "loss_curve": loss[None]}

    def predict_scores(self, params, X):
        """Joint log-likelihood ``(n, C)``: log prior + Σ_f log N(x_f).

        ``X`` is centered on the stored global shift before the
        expanded quadratic — the (x²) term would otherwise cancel
        catastrophically in f32 for large-offset features (the same
        hazard the fit's shifted moments avoid).
        """
        Xs = X.astype(jnp.float32) - params["shift"][None, :]
        mean, var = params["mean"], params["var"]  # (C, F), shifted
        inv = 1.0 / var
        # Σ_f (x_f − μ_cf)² / σ²_cf expanded so the cross term is one
        # (n, F)@(F, C) matmul instead of an (n, C, F) broadcast
        quad = (
            (Xs * Xs) @ inv.T
            - 2.0 * (Xs @ (mean * inv).T)
            + jnp.sum(mean * mean * inv, axis=1)[None, :]
        )
        log_norm = jnp.sum(jnp.log(var) + _LOG_2PI, axis=1)[None, :]
        return params["log_prior"][None, :] - 0.5 * (quad + log_norm)


def _weighted_class_counts(Xc, y, w, C, axis_name):
    """Shared count-NB statistics: per-class weight totals, the global
    weight sum, the (C, F) weighted feature counts, and log priors."""
    Yw = jax.nn.one_hot(y, C, dtype=jnp.float32).T * w[None, :]
    cls_w = maybe_psum(Yw.sum(axis=1), axis_name)          # (C,)
    w_sum = jnp.maximum(cls_w.sum(), 1e-12)
    counts = maybe_psum(Yw @ Xc, axis_name)                # (C, F)
    log_prior = jnp.log(jnp.maximum(cls_w, 1e-12) / w_sum)
    return cls_w, w_sum, counts, log_prior


def _weighted_nll(learner, params, X, y, w, w_sum, axis_name):
    """Weighted mean NLL of the fitted model (loss curve/report)."""
    logp = jax.nn.log_softmax(learner.predict_scores(params, X), axis=-1)
    nll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
    return maybe_psum(jnp.sum(w * nll), axis_name) / w_sum


class MultinomialNB(BaseLearner):
    """Weighted multinomial naive Bayes over count features.

    Spark ML's ``NaiveBayes`` default model type [B:5, SURVEY §1 L3]:
    per-class feature-count distributions with Laplace smoothing
    ``alpha``. The fit is ONE ``(C, n) @ (n, F)`` weighted-count matmul.
    Features must be non-negative (counts / tf-idf); like Spark, the
    result is undefined on negative inputs (jitted code cannot raise
    data-dependent errors).
    """

    task = "classification"
    streamable = False  # closed-form; one pass, no gradient stream

    def __init__(self, alpha: float = 1.0):
        if alpha < 0:
            raise ValueError(f"alpha must be >= 0, got {alpha}")
        self.alpha = alpha

    def init_params(self, key, n_features, n_outputs):
        del key
        return {
            "log_prior": jnp.zeros((n_outputs,), jnp.float32),
            "log_theta": jnp.zeros((n_outputs, n_features), jnp.float32),
        }

    def flops_per_fit(self, n_rows, n_features, n_outputs):
        return float(2 * n_rows * n_features * n_outputs
                     + 4 * n_rows * n_outputs)

    def fit(self, params, X, y, sample_weight, key, *,
            axis_name=None, prepared=None) -> tuple[Params, Aux]:
        del key, prepared
        C = params["log_theta"].shape[0]
        X = X.astype(jnp.float32)
        w = sample_weight.astype(jnp.float32)
        _, w_sum, counts, log_prior = _weighted_class_counts(
            X, y, w, C, axis_name
        )
        # alpha=0 with a zero (class, feature) count would give
        # log(0) = -inf and then 0 * -inf = NaN in the score matmul;
        # the floor keeps the cell finite (huge-negative, as intended)
        sm = jnp.maximum(counts + self.alpha, 1e-12)
        log_theta = jnp.log(sm) - jnp.log(sm.sum(axis=1))[:, None]
        params = {"log_prior": log_prior, "log_theta": log_theta}
        loss = _weighted_nll(self, params, X, y, w, w_sum, axis_name)
        return params, {"loss": loss, "loss_curve": loss[None]}

    def predict_scores(self, params, X):
        return (
            params["log_prior"][None, :]
            + X.astype(jnp.float32) @ params["log_theta"].T
        )


class BernoulliNB(BaseLearner):
    """Weighted Bernoulli naive Bayes over binarized features.

    Spark ML ``NaiveBayes(modelType="bernoulli")`` [B:5]. ``binarize``
    is the threshold mapping features to {0, 1} (sklearn convention);
    ``alpha`` the Laplace smoothing. Closed-form weighted-count fit,
    one matmul, exactly data-parallel through ``maybe_psum``.
    """

    task = "classification"
    streamable = False

    def __init__(self, alpha: float = 1.0, binarize: float = 0.0):
        if alpha < 0:
            raise ValueError(f"alpha must be >= 0, got {alpha}")
        self.alpha = alpha
        self.binarize = binarize

    def init_params(self, key, n_features, n_outputs):
        del key
        return {
            "log_prior": jnp.zeros((n_outputs,), jnp.float32),
            "log_theta": jnp.full(
                (n_outputs, n_features), -0.6931472, jnp.float32
            ),
            "log_1m_theta": jnp.full(
                (n_outputs, n_features), -0.6931472, jnp.float32
            ),
        }

    def flops_per_fit(self, n_rows, n_features, n_outputs):
        return float(2 * n_rows * n_features * n_outputs
                     + 4 * n_rows * n_outputs)

    def fit(self, params, X, y, sample_weight, key, *,
            axis_name=None, prepared=None) -> tuple[Params, Aux]:
        del key, prepared
        C = params["log_theta"].shape[0]
        Xb = (X > self.binarize).astype(jnp.float32)
        w = sample_weight.astype(jnp.float32)
        cls_w, w_sum, counts, log_prior = _weighted_class_counts(
            Xb, y, w, C, axis_name
        )
        theta = (counts + self.alpha) / (
            jnp.maximum(cls_w, 1e-12) + 2.0 * self.alpha
        )[:, None]
        # alpha=0 can put theta at exactly 0 or 1; log/log1p would be
        # -inf and poison scores with 0 * -inf = NaN. The margin must
        # survive float32: 1 - 1e-12 rounds back to exactly 1.0f
        # (nextafter(1, 0) is 1 - 6e-8), so clip a float32-wide 1e-6
        theta = jnp.clip(theta, 1e-6, 1.0 - 1e-6)
        params = {
            "log_prior": log_prior,
            "log_theta": jnp.log(theta),
            "log_1m_theta": jnp.log1p(-theta),
        }
        # score Xb directly — routing through predict_scores would
        # re-binarize the already-binary matrix, corrupting the
        # reported loss whenever binarize is outside [0, 1)
        logp = jax.nn.log_softmax(self._scores_from_binary(params, Xb),
                                  axis=-1)
        nll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
        loss = maybe_psum(jnp.sum(w * nll), axis_name) / w_sum
        return params, {"loss": loss, "loss_curve": loss[None]}

    @staticmethod
    def _scores_from_binary(params, Xb):
        lt, l1m = params["log_theta"], params["log_1m_theta"]
        # Σ_f x·logθ + (1−x)·log(1−θ) = Σ log(1−θ) + x·(logθ − log(1−θ))
        return (
            params["log_prior"][None, :]
            + jnp.sum(l1m, axis=1)[None, :]
            + Xb @ (lt - l1m).T
        )

    def predict_scores(self, params, X):
        return self._scores_from_binary(
            params, (X > self.binarize).astype(jnp.float32)
        )
