"""Accelerated-failure-time survival regression (Weibull AFT).

The last Spark ML predictor family [VERDICT r2 missing#5, ask#7]: the
reference's plugin slot accepts any Spark Predictor, including
``AFTSurvivalRegression`` (censored survival times with a ``censorCol``
of 1.0 = event observed / 0.0 = right-censored). The censor column
rides the ensemble engine's per-row ``aux`` channel — drawn rows keep
their censor flags because bagging here resamples via Poisson *weights*,
never by index shuffling [SURVEY §7.2].

Model (Spark-parity parameterization): survival time T follows a
Weibull distribution with ``log T = μ + σ·ε``, ``μ = X·β + b``, ``ε``
standard (minimum) extreme value. With ``z = (log t − μ)/σ`` and censor
indicator ``δ``:

    log L_i = δ·(z − log σ) − e^z      (+ δ·(−log t), a constant)

The fit maximizes the Poisson-weighted log-likelihood over
``(β, b, log σ)`` with ``max_iter`` full-batch Adam steps — a fixed
iteration count so the whole fit is one traced XLA program, vmap-able
over replicas like every other learner. Row sums go through
``maybe_psum`` so the same code runs data-sharded on a mesh.

``predict_scores`` returns ``e^μ`` (Spark's ``prediction`` column);
``predict_quantiles`` gives Weibull quantiles like Spark's
``quantilesCol``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from spark_bagging_tpu.models.base import BaseLearner, augment_bias
from spark_bagging_tpu.ops.reduce import maybe_psum

_EPS = 1e-8


class AFTSurvivalRegression(BaseLearner):
    """Weibull accelerated-failure-time regressor with right censoring.

    Parameters mirror the learner conventions elsewhere: ``l2``
    penalizes ``β`` (never the bias or ``log σ``); ``precision`` pins
    MXU matmul precision (gradient math tolerates "high"; see
    models/mlp.py for the rationale).
    """

    task = "regression"
    # Streams through the SGD engine with the censor column designated
    # via fit_stream's ``aux_col`` (the Spark censorCol-as-a-column
    # convention); aux=None degenerates to fully-observed Weibull.
    streamable = True
    uses_aux = True

    def __init__(
        self,
        max_iter: int = 200,
        lr: float = 0.05,
        l2: float = 1e-4,
        precision: str = "high",
    ):
        if max_iter < 1:
            raise ValueError(f"max_iter must be >= 1, got {max_iter}")
        self.max_iter = max_iter
        self.lr = lr
        self.l2 = l2
        self.precision = precision

    def init_params(self, key, n_features, n_outputs):
        del key, n_outputs  # deterministic zero init, scalar output
        return {
            "beta": jnp.zeros((n_features + 1,), jnp.float32),
            "log_sigma": jnp.zeros((), jnp.float32),
        }

    def predict_scores(self, params, X):
        """Predicted survival time ``e^μ`` (Spark's prediction col)."""
        Xb = augment_bias(X.astype(jnp.float32))
        return jnp.exp(Xb @ params["beta"])

    def predict_quantiles(self, params, X, probs):
        """Weibull quantiles ``t_p = exp(μ + σ·log(−log(1−p)))`` for
        each p in ``probs`` — Spark's quantilesCol. Returns
        ``(n, len(probs))``."""
        Xb = augment_bias(X.astype(jnp.float32))
        mu = Xb @ params["beta"]
        sigma = jnp.exp(params["log_sigma"])
        p = jnp.asarray(probs, jnp.float32)
        return jnp.exp(
            mu[:, None] + sigma * jnp.log(-jnp.log1p(-p))[None, :]
        )

    def flops_per_fit(self, n_rows, n_features, n_outputs):
        del n_outputs
        n, d = n_rows, n_features + 1
        # fwd (n,d)@(d,) + bwd ≈ 2x, per Adam step
        return float(self.max_iter * 6 * n * d)

    def _nll_rows(self, params, X, y, delta):
        """Per-row negative Weibull AFT log-likelihood (shared by the
        in-memory Newton-free Adam fit and the streaming row_loss)."""
        logt = jnp.log(jnp.maximum(y.astype(jnp.float32), _EPS))
        Xb = augment_bias(X.astype(jnp.float32))
        mu = Xb @ params["beta"]
        sigma = jnp.exp(params["log_sigma"])
        z = (logt - mu) / sigma
        return -(delta * (z - params["log_sigma"]) - jnp.exp(z))

    # -- streaming contract (aux-carrying SGD engine) -------------------

    def row_loss(self, params, X, y, aux=None):
        delta = (
            jnp.ones_like(y, dtype=jnp.float32) if aux is None
            else aux.astype(jnp.float32)
        )
        return self._nll_rows(params, X, y, delta)

    def penalty(self, params):
        return 0.5 * self.l2 * jnp.sum(params["beta"][:-1] ** 2)

    def sgd_step_flops(self, chunk_rows, n_features, n_outputs):
        del n_outputs
        return float(6 * chunk_rows * (n_features + 1))

    def fit_workset_bytes(self, n_rows, n_features, n_outputs):
        del n_outputs
        # the per-replica (n, d+1) bias-augmented design copy (built
        # inside the vmapped fit, like linear/glm) + a handful of (n,)
        # working vectors (z, loglik, weights, grads)
        return float(4 * n_rows * (n_features + 1) + 24 * n_rows)

    def fit(self, params, X, y, sample_weight, key, *, axis_name=None,
            prepared=None, aux=None):
        del key, prepared
        X = X.astype(jnp.float32)
        w = sample_weight.astype(jnp.float32)
        # δ: 1.0 = event observed, 0.0 = right-censored (Spark's
        # censorCol convention); None ⇒ fully observed (plain Weibull
        # regression)
        delta = (
            jnp.ones_like(w) if aux is None else aux.astype(jnp.float32)
        )
        w_sum = maybe_psum(jnp.sum(w), axis_name)

        def nll(p):
            data = maybe_psum(
                jnp.sum(w * self._nll_rows(p, X, y, delta)), axis_name
            )
            return data / jnp.maximum(w_sum, _EPS) + self.penalty(p)

        opt = optax.adam(self.lr)

        with jax.default_matmul_precision(self.precision):

            def step(carry, _):
                p, opt_state = carry
                loss, g = jax.value_and_grad(nll)(p)
                updates, opt_state = opt.update(g, opt_state, p)
                return (optax.apply_updates(p, updates), opt_state), loss

            (params, _), losses = jax.lax.scan(
                step, (params, opt.init(params)), None,
                length=self.max_iter,
            )
            # losses[i] is evaluated BEFORE step i's update, so
            # losses[-1] is one step stale; report the loss at the
            # final params (and the curve), like every other learner
            final = nll(params)
        return params, {"loss": final, "loss_curve": losses}
