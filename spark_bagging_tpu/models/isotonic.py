"""Isotonic regression — Spark ML's ``IsotonicRegression`` analog.

Spark ships single-feature isotonic regression as a stock Predictor
[B:5, SURVEY §1 L3], fit by pool-adjacent-violators. PAV is inherently
sequential — it cannot jit or ``vmap`` as a static-shape program, which
is why this family was initially a documented non-goal. The TPU-native
formulation sidesteps PAV entirely:

1. **Quantile-bin x** into ``n_bins`` buckets (the tree engine's
   binning philosophy); accumulate weighted (Σw, Σw·y) per bin as ONE
   ``(B, n) @ (n, 2)`` matmul.
2. **Closed-form minimax**: the isotonic fit at bin i is
   ``max_{j≤i} min_{k≥i} mean(y_j..y_k)`` — an O(B²) table of span
   means from prefix sums, a reversed cummin over k, a cummax over j.
   Every step is a dense vectorized op on a (B, B) array (64 KB at
   B=128): static shapes, jit-clean, trivially ``vmap``-able over
   replicas.

Exactness: identical to PAV whenever every distinct x value occupies
its own bin — guaranteed when each value holds at least ``n/n_bins``
rows (balanced duplicates), and in particular whenever
``n ≤ n_bins``. Quantile edges stride by ``n/n_bins`` ROWS, so a rare
value inside a skewed distribution can share a bin with its neighbor;
then the fit is isotonic regression on the binned means — the same
binning approximation the tree engine makes, and the bagging ensemble
averages over replicas anyway.
Prediction interpolates linearly between bin centers (Spark's
prediction semantics). ``increasing=False`` fits the antitonic case by
sign-flipping y. Weighted fits treat Poisson counts as exact
multiplicities via the bin accumulators [SURVEY §7 hard-part 2]; row
reductions ride ``maybe_psum``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_bagging_tpu.models.base import BaseLearner
from spark_bagging_tpu.models.tree import (
    _psum_average_edges,
    _quantile_edges,
)
from spark_bagging_tpu.ops.reduce import maybe_psum

_EPS = 1e-12


class IsotonicRegression(BaseLearner):
    """Monotone single-feature regression (uses column 0 of X, like
    Spark's featuresCol + featureIndex convention)."""

    task = "regression"
    streamable = False  # closed-form over bins; no gradient stream

    def __init__(self, n_bins: int = 128, increasing: bool = True):
        if n_bins < 2:
            raise ValueError(f"n_bins must be >= 2, got {n_bins}")
        self.n_bins = n_bins
        self.increasing = increasing

    def init_params(self, key, n_features, n_outputs):
        del key, n_features, n_outputs
        B = self.n_bins
        return {
            "centers": jnp.zeros((B,), jnp.float32),
            "values": jnp.zeros((B,), jnp.float32),
        }

    # -- replica-invariant binning (computed ONCE via the prepare
    #    hook, not per replica under vmap; subspace draws slice it) ---

    def prepare(self, X, *, axis_name=None, row_mask=None):
        interior, n_valid = _quantile_edges(X, row_mask, self.n_bins)
        return {
            "interior": _psum_average_edges(interior, n_valid, axis_name)
        }  # (F, B-1)

    def gather_subspace(self, prepared, idx):
        return {"interior": prepared["interior"][idx]}

    def flops_per_fit(self, n_rows, n_features, n_outputs):
        del n_features, n_outputs
        B = self.n_bins
        # O(n) segment-sum binning (searchsorted ~log B + two adds per
        # row — the dense one-hot matmul this replaced must NOT be
        # charged, or reported MFU inflates ~B-fold) + the O(B²)
        # minimax table
        import math

        return float(n_rows * (math.ceil(math.log2(B)) + 4) + 6 * B * B)

    def fit(self, params, X, y, sample_weight, key, *, axis_name=None,
            prepared=None):
        del params
        del key
        B = self.n_bins
        x = X[:, 0].astype(jnp.float32)
        yf = y.astype(jnp.float32)
        if not self.increasing:
            yf = -yf
        w = sample_weight.astype(jnp.float32)

        # bin GEOMETRY may ignore weights, the STATISTICS must not —
        # the tree convention; edges come from the prepare() hook so
        # replicas share ONE binning pass
        if prepared is None:
            prepared = self.prepare(X, axis_name=axis_name)
        interior = prepared["interior"][0]               # (B-1,)
        idx = jnp.searchsorted(interior, x, side="right")  # (n,) in [0,B)

        # segment_sum, not a dense (n, B) one-hot: bin accumulation
        # stays O(n + B) memory at any row count (a 45M-row f32
        # one-hot would be ~23 GB)
        stats = maybe_psum(
            jax.ops.segment_sum(
                jnp.stack([w, w * yf, w * x], axis=1), idx,
                num_segments=B,
            ),
            axis_name,
        )                                                  # (B, 3)
        W = stats[:, 0]
        Swy = stats[:, 1]
        # bin centers = weighted mean x per bin; empty bins fall back
        # to the midpoint of their edges (predict interpolation anchor)
        lo = jnp.concatenate([interior[:1], interior])
        hi = jnp.concatenate([interior, interior[-1:]])
        centers = jnp.where(
            W > 0, stats[:, 2] / jnp.maximum(W, _EPS), 0.5 * (lo + hi)
        )

        # minimax isotonic fit over bins from prefix sums:
        # A[j, k] = mean(y over bins j..k); empty spans -> +inf so the
        # min step skips them, rows that stay +inf -> -inf so the max
        # step skips those
        cW = jnp.concatenate([jnp.zeros((1,)), jnp.cumsum(W)])
        cS = jnp.concatenate([jnp.zeros((1,)), jnp.cumsum(Swy)])
        Wspan = cW[None, 1:] - cW[:-1, None]             # (B, B) j,k
        Sspan = cS[None, 1:] - cS[:-1, None]
        valid = Wspan > 0
        A = jnp.where(valid, Sspan / jnp.maximum(Wspan, _EPS), jnp.inf)
        # min over k >= i: reversed cumulative min along k
        Mink = jax.lax.cummin(A, axis=1, reverse=True)   # (B, B) j,i
        R = jnp.where(jnp.isfinite(Mink), Mink, -jnp.inf)
        # max over j <= i: cumulative max along j
        iso = jax.lax.cummax(R, axis=0)                  # (B, B) j,i
        values = jnp.diagonal(iso)                       # (B,)
        # regions with no data anywhere reachable: global mean
        gmean = jnp.sum(Swy) / jnp.maximum(jnp.sum(W), _EPS)
        values = jnp.where(jnp.isfinite(values), values, gmean)
        if not self.increasing:
            values = -values

        # weighted mean squared error for the report
        pred = jnp.interp(x, centers, values)
        target = y.astype(jnp.float32)
        w_sum = maybe_psum(jnp.sum(w), axis_name)
        mse = maybe_psum(
            jnp.sum(w * (pred - target) ** 2), axis_name
        ) / jnp.maximum(w_sum, _EPS)
        return (
            {"centers": centers, "values": values},
            {"loss": mse, "loss_curve": mse[None]},
        )

    def predict_scores(self, params, X):
        """Linear interpolation between bin centers (Spark prediction
        semantics); constant extrapolation beyond the data range."""
        return jnp.interp(
            X[:, 0].astype(jnp.float32),
            params["centers"], params["values"],
        )
