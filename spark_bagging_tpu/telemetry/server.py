"""Live exposition server — scrape the process instead of reading dumps.

Everything before this was passive observability: an in-process
registry plus offline JSONL/Prometheus dumps. This module is the live
half — a zero-dependency stdlib ``http.server`` endpoint an operator
(or a Prometheus scraper, or ``curl``) points at a serving process:

- ``GET /metrics`` — the registry in Prometheus text exposition
  format, straight off the live process (``# HELP``/``# TYPE`` lines
  included);
- ``GET /healthz`` — aggregate liveness from every registered health
  source (micro-batcher queue depth vs. bound, last-batch age, closed
  flag; model registry live versions). 200 when every source is
  healthy, 503 otherwise — load-balancer-compatible;
- ``GET /varz`` — one JSON snapshot: metrics (with per-histogram
  p50/p95/p99 quantiles and exemplar trace ids), health detail,
  process info;
- ``GET /debug/spans`` — recent span events from the flight
  recorder's ring (``?trace_id=`` filters to one request's tree);
- ``GET /debug/runs`` — the run registry (every ``capture()`` window
  this process opened);
- ``GET /debug/workload`` — the active workload recorder's capture
  summary (request count, duration, rps, epochs) while recording is
  on — the live view of the record half of record→replay→report;
- ``GET /alerts`` — the process-default alert engine's rule states
  (active alerts, fire/resolve/suppress counts); each scrape runs one
  evaluation pass, so a Prometheus-less deployment still gets alert
  transitions just by polling;
- ``GET /debug/drift`` — every attached quality monitor's drift
  summary (per-feature PSI/KS vs the training reference, live
  medians, disagreement stats);
- ``GET /debug/tail`` — the tail-latency explainer
  (``telemetry/perf.py``): the slowest retained requests, each joined
  against the flight recorder's concurrent events into a verdict
  (queue-dominated / compile-absorbed / retry-inflated /
  degraded-path / genuinely-slow-forward);
- ``GET /debug/history`` — the longitudinal verification history
  (``telemetry/history.py``): the newest trend-store records
  (scenario/bench/tier runs) plus the ``compare_trend`` verdict over
  the full store — digest flips are findings, noise-band numeric
  wobble is not;
- ``GET /debug/capacity`` — the capacity & residency plane
  (``telemetry/capacity.py``): per-owner ledger reconciled against
  the program cache, the per-resident eviction-decision explainer
  (LRU position, demand rank/class, bytes reclaimable, last-hit age),
  demand table, recent owner-attributed evictions, device memory;
- ``GET /debug/tenancy`` — the installed tenant fleet
  (``spark_bagging_tpu/tenancy/``): per-tenant specs, admission
  pressure state + decision counts, WFQ service audit, residency
  residents/demotions/restores/pin violations, refit-budget state,
  per-tenant quarantine state (trips/backoff/probes), per-tenant
  latency p99s;
- ``GET /debug/profile?seconds=N`` — on-demand live device profiling:
  starts a single-flight ``jax.profiler`` capture that auto-stops
  after N seconds (hard-capped) into ``telemetry_dir()/profiles/``;
  409 while one is already running, ``?action=stop`` ends it early;
- ``GET /fleet/metrics`` / ``/fleet/varz`` / ``/fleet/healthz`` /
  ``/fleet/incidents`` — the fleet plane (``telemetry/fleet.py``):
  when a :class:`~spark_bagging_tpu.telemetry.fleet.FleetAggregator`
  is installed, each scrape ticks it (interval-limited) and serves
  the exactly-merged N-process view — summed counters,
  ``process=``-labeled gauges, bucket-merged histograms with exact
  fleet quantiles, quorum health over peer healthz + scrape
  staleness, and the correlated incident timeline.

Opt-in, two ways: ``telemetry.start_server(port)`` from code, or the
``SBT_METRICS_PORT`` environment variable (checked at package import;
port 0 picks an ephemeral port). The server runs on one daemon thread
(requests themselves are handled on short-lived threads); when it is
not started, nothing in this module runs — the serving hot path's
zero-overhead contract is untouched. Binds loopback by default:
metrics can leak data shapes and model names, so exposing beyond the
host is a deliberate ``host=`` choice.

Health sources register WEAKLY: a batcher garbage-collected with its
serving stack disappears from ``/healthz`` instead of pinning the
object alive or reporting a ghost. A closed-but-referenced batcher
reports unhealthy by design — drop the reference once it is retired.
(Close first: an un-closed batcher's worker thread holds a strong
reference to it, so abandoning one without ``close()``/``retire()``
leaks the thread AND keeps its health entry live.)
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
from urllib.parse import parse_qs, urlparse
import weakref

from spark_bagging_tpu.analysis.locks import make_lock

_module_lock = make_lock("telemetry.server")
_server: ThreadingHTTPServer | None = None
_thread: threading.Thread | None = None
_t_start: float | None = None

# handle -> (source name, weakref to owner, bound health fn taking the
# live owner). Owner death removes the entry lazily on read.
_health_sources: dict[int, tuple[str, Any, Callable[[Any], dict]]] = {}
_health_seq = [0]


def register_health_source(
    name: str, owner: Any, fn: Callable[[Any], dict],
) -> int:
    """Register ``fn(owner) -> dict`` as a ``/healthz`` contributor.

    The dict must carry ``healthy: bool``; everything else is detail
    surfaced verbatim. ``owner`` is held by weak reference. Returns a
    handle for :func:`remove_health_source`.
    """
    with _module_lock:
        # prune dead owners here too, not only in health_report():
        # a process that never serves /healthz (no server started)
        # but churns through batchers must not grow this dict forever
        for h in [h for h, (_, r, _f) in _health_sources.items()
                  if r() is None]:
            del _health_sources[h]
        _health_seq[0] += 1
        handle = _health_seq[0]
        _health_sources[handle] = (name, weakref.ref(owner), fn)
    return handle


def remove_health_source(handle: int) -> None:
    with _module_lock:
        _health_sources.pop(handle, None)


def clear_health_sources() -> None:
    """Drop every registered source (test isolation; embedders that
    rebuild their serving stack in-process)."""
    with _module_lock:
        _health_sources.clear()


def health_report() -> dict[str, Any]:
    """Aggregate health: ``{"healthy": bool, "sources": {...}}``.
    Healthy when every live source is (an empty source set is healthy:
    nothing is wrong, there is just nothing serving yet)."""
    with _module_lock:
        items = list(_health_sources.items())
    sources: dict[str, dict] = {}
    healthy = True
    dead: list[int] = []
    for handle, (name, ref, fn) in items:
        owner = ref()
        if owner is None:
            dead.append(handle)
            continue
        try:
            detail = dict(fn(owner))
        # sbt-lint: disable=swallowed-fault — the fault IS the report: surfaced as healthy=False with the error in the /healthz body
        except Exception as e:  # noqa: BLE001 — a broken health probe
            # IS unhealth, not a reason to take the endpoint down
            detail = {"healthy": False, "error": repr(e)}
        healthy = healthy and bool(detail.get("healthy"))
        sources[f"{name}#{handle}"] = detail
    if dead:
        with _module_lock:
            for handle in dead:
                _health_sources.pop(handle, None)
    return {"healthy": healthy, "sources": sources}


def _refresh_process_gauges() -> tuple[float | None, int | None]:
    """Sample uptime + RSS and mirror them as ``sbt_process_*``
    registry gauges. Called from BOTH exposition routes — a
    Prometheus deployment that only ever scrapes ``/metrics`` (the
    normal setup) must see fresh values, not ones frozen at the last
    manual ``/varz`` curl. Returns the pair for ``/varz``'s JSON."""
    from spark_bagging_tpu.telemetry.state import STATE
    from spark_bagging_tpu.utils.memory import host_rss_bytes

    uptime = (time.monotonic() - _t_start
              if _t_start is not None else None)
    rss = host_rss_bytes()
    if STATE.enabled:
        if uptime is not None:
            STATE.registry.set("sbt_process_uptime_seconds", uptime)
        if rss is not None:
            STATE.registry.set("sbt_process_rss_bytes", float(rss))
        # device residency twins [ISSUE 16]: honest-None on backends
        # without memory stats (CPU) — the gauges simply don't exist
        # there, they never report a made-up 0
        from spark_bagging_tpu.utils.memory import device_memory_stats

        for d in device_memory_stats() or ():
            labels = {"device": str(d["id"])}
            STATE.registry.set("sbt_process_device_bytes_in_use",
                               float(d["bytes_in_use"]), labels)
            STATE.registry.set("sbt_process_device_bytes_limit",
                               float(d["bytes_limit"]), labels)
            if d["peak_bytes_in_use"] is not None:
                STATE.registry.set("sbt_process_device_peak_bytes",
                                   float(d["peak_bytes_in_use"]),
                                   labels)
        # capacity gauge refresh: scrape-time, like rss — the alert
        # rules (default_capacity_rules) read headroom/cold-resident
        # off the registry, so each scrape re-derives them
        from spark_bagging_tpu.telemetry import capacity

        plane = capacity.ACTIVE
        if plane is not None:
            plane.export_gauges()
    return uptime, rss


def _varz() -> dict[str, Any]:
    from spark_bagging_tpu.telemetry import recorder
    from spark_bagging_tpu.telemetry.state import STATE

    uptime, rss = _refresh_process_gauges()
    out = {
        "ts": time.time(),
        "pid": os.getpid(),
        "uptime_seconds": uptime,
        "rss_bytes": rss,
        "telemetry_enabled": STATE.enabled,
        "health": health_report(),
        "metrics": STATE.registry.snapshot(quantiles=True),
    }
    rec = recorder.get()
    if rec is not None:
        # the peer-side incident feed: dump records + ring trigger
        # events — what a fleet aggregator's /fleet/incidents
        # correlation consumes from this process's scrape
        out["flight"] = {"armed": rec.armed, **rec.timeline_feed()}
    return out


def _debug_spans(query: dict[str, list[str]]) -> dict[str, Any]:
    from spark_bagging_tpu.telemetry import recorder

    rec = recorder.get()
    if rec is None:
        return {"spans": [], "note": "flight recorder not armed"}
    spans = rec.events(kind="span")
    trace_id = (query.get("trace_id") or [None])[0]
    if trace_id:
        spans = [
            s for s in spans
            if s.get("trace_id") == trace_id
            or trace_id in (s.get("links") or ())
        ]
    try:
        limit = max(0, int((query.get("limit") or ["256"])[0]))
    except ValueError:
        # garbage ?limit= falls back to the default window rather than
        # 500ing the scrape (negative values are clamped above — a raw
        # spans[-limit:] would have INVERTED the slice and returned
        # nearly the whole ring)
        limit = 256
    # limit=0 must mean "none", but spans[-0:] slices from the START
    # and would return the whole ring
    return {"spans": spans[-limit:] if limit else []}


def _debug_workload() -> dict[str, Any]:
    from spark_bagging_tpu.telemetry import workload

    rec = workload.active()
    if rec is None:
        return {
            "recording": False,
            "note": "no workload recorder active; start one with "
                    "telemetry.workload.record()",
        }
    return rec.summary()


def _debug_drift() -> dict[str, Any]:
    from spark_bagging_tpu.telemetry import quality

    return quality.debug_summary()


def _debug_history(query: dict[str, list[str]]) -> dict[str, Any]:
    from spark_bagging_tpu.telemetry import history

    try:
        limit = max(0, int((query.get("limit") or ["32"])[0]))
    except ValueError:
        limit = 32
    return history.history_report(limit=limit)


def _debug_tail(query: dict[str, list[str]]) -> dict[str, Any]:
    from spark_bagging_tpu.telemetry import perf

    try:
        limit = max(1, int((query.get("limit") or ["8"])[0]))
    except ValueError:
        limit = 8
    try:
        window_s = float((query.get("window_s") or ["1.0"])[0])
    except ValueError:
        window_s = 1.0
    tenant = (query.get("tenant") or [None])[0]
    return perf.tail_report(limit=limit, window_s=window_s,
                            tenant=tenant)


def _debug_capacity(query: dict[str, list[str]]) -> dict[str, Any]:
    from spark_bagging_tpu.telemetry import capacity

    try:
        limit = max(1, int((query.get("limit") or ["64"])[0]))
    except ValueError:
        limit = 64
    return capacity.capacity_report(limit=limit)


def _debug_tenancy() -> dict[str, Any]:
    """The installed :class:`~spark_bagging_tpu.tenancy.fleet.
    TenantFleet`'s full policy report — admission state machine, WFQ
    audit, residency transcript counts, refit budget, quarantine
    machine state. An honest explicit shape when no fleet is installed
    (a single-model process is the common case, not an error)."""
    from spark_bagging_tpu import tenancy

    fleet = tenancy.get()
    if fleet is None:
        return {"enabled": False,
                "note": "no TenantFleet installed (tenancy.install)"}
    fleet.export_gauges()
    return {"enabled": True, **fleet.report()}


def _debug_profile(query: dict[str, list[str]]) -> tuple[int, dict]:
    """On-demand live device profiling: ``?seconds=N`` starts a
    jax.profiler capture that auto-stops after N seconds (clamped to
    the hard maximum) into ``telemetry_dir()/profiles/``; a second
    request while one runs is rejected with 409 (the single-flight
    guard shared with ``utils.profiling.trace()``); ``?action=stop``
    ends a capture early."""
    from spark_bagging_tpu.utils import profiling

    action = (query.get("action") or ["start"])[0]
    if action == "stop":
        info = profiling.stop_profile()
        if info is None:
            return 200, {"stopped": False,
                         "note": "no capture was running"}
        return 200, {"stopped": True, **info}
    if action != "start":
        return 400, {"error": f"unknown action {action!r} "
                              "(start or stop)"}
    try:
        seconds = float((query.get("seconds") or ["5"])[0])
    except ValueError:
        return 400, {"error": "seconds must be a number"}
    if seconds <= 0:
        return 400, {"error": f"seconds must be > 0, got {seconds}"}
    try:
        info = profiling.start_profile(max_seconds=seconds)
    except profiling.ProfilerBusy as e:
        return 409, {"error": str(e), "active": profiling.profile_active()}
    return 200, {
        "started": True,
        "max_seconds_cap": profiling.PROFILE_MAX_SECONDS,
        "view": "tensorboard --logdir " + str(info["dir"]),
        **info,
    }


def _alerts() -> dict[str, Any]:
    from spark_bagging_tpu.telemetry import alerts

    eng = alerts.get()
    if eng is None:
        return {
            "rules": [], "active": [],
            "note": "no alert engine installed; install rules with "
                    "telemetry.alerts.install([...])",
        }
    # scrape-driven evaluation: polling /alerts IS the tick loop for
    # deployments that run no evaluator of their own
    eng.evaluate()
    return eng.state()


def _fleet(route: str):
    """Dispatch a ``/fleet/*`` route against the process-default
    aggregator: each scrape ticks it (interval-limited — a tight curl
    loop cannot hammer the peers), then serves the requested merged
    view. ``(status, body, content_type|None)``; JSON when None."""
    from spark_bagging_tpu.telemetry import fleet
    from spark_bagging_tpu.telemetry.registry import render_prometheus

    agg = fleet.get()
    if agg is None:
        return 404, {
            "error": "no fleet aggregator installed; install one with "
                     "telemetry.fleet.install(FleetAggregator([...]))",
        }, None
    agg.tick()
    if route == "metrics":
        return 200, render_prometheus(agg.merged_snapshot()), \
            "text/plain; version=0.0.4"
    if route == "varz":
        return 200, agg.fleet_varz(), None
    if route == "healthz":
        report = agg.fleet_health()
        return (200 if report["healthy"] else 503), report, None
    if route == "incidents":
        return 200, agg.incident_timeline(), None
    return 404, {"error": f"no route /fleet/{route}"}, None


def _debug_runs() -> dict[str, Any]:
    from spark_bagging_tpu.telemetry import sinks

    active = {r.run_id for r in [sinks.current_run()] if r is not None}
    return {
        "runs": [
            {
                "run_id": r.run_id,
                "label": r.label,
                "path": r.path,
                "t_start": r.t_start,
                "n_events": r.n_events,
                "active": r.run_id in active,
            }
            for r in sinks.runs()
        ]
    }


class _Handler(BaseHTTPRequestHandler):
    server_version = "sbt-telemetry/1"

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        url = urlparse(self.path)
        query = parse_qs(url.query)
        try:
            if url.path == "/metrics":
                from spark_bagging_tpu.telemetry.registry import (
                    render_prometheus,
                )
                from spark_bagging_tpu.telemetry.state import STATE

                _refresh_process_gauges()
                body = render_prometheus(STATE.registry.snapshot())
                self._send(200, body, "text/plain; version=0.0.4")
            elif url.path == "/healthz":
                report = health_report()
                self._send_json(200 if report["healthy"] else 503, report)
            elif url.path == "/varz":
                self._send_json(200, _varz())
            elif url.path == "/debug/spans":
                self._send_json(200, _debug_spans(query))
            elif url.path == "/debug/runs":
                self._send_json(200, _debug_runs())
            elif url.path == "/debug/workload":
                self._send_json(200, _debug_workload())
            elif url.path == "/alerts":
                self._send_json(200, _alerts())
            elif url.path == "/debug/drift":
                self._send_json(200, _debug_drift())
            elif url.path == "/debug/tail":
                self._send_json(200, _debug_tail(query))
            elif url.path == "/debug/history":
                self._send_json(200, _debug_history(query))
            elif url.path == "/debug/capacity":
                self._send_json(200, _debug_capacity(query))
            elif url.path == "/debug/tenancy":
                self._send_json(200, _debug_tenancy())
            elif url.path == "/debug/profile":
                code, body = _debug_profile(query)
                self._send_json(code, body)
            elif url.path.startswith("/fleet/"):
                code, body, ctype = _fleet(url.path[len("/fleet/"):])
                if ctype is not None:
                    self._send(code, body, ctype)
                else:
                    self._send_json(code, body)
            elif url.path == "/":
                self._send_json(200, {
                    "endpoints": [
                        "/metrics", "/healthz", "/varz", "/alerts",
                        "/debug/spans", "/debug/runs",
                        "/debug/workload", "/debug/drift",
                        "/debug/tail", "/debug/history",
                        "/debug/capacity", "/debug/tenancy",
                        "/debug/profile",
                        "/fleet/metrics", "/fleet/varz",
                        "/fleet/healthz", "/fleet/incidents",
                    ],
                })
            else:
                self._send_json(404, {"error": f"no route {url.path}"})
        except (BrokenPipeError, ConnectionResetError):
            # the client hung up mid-response (scrape timeout, Ctrl-C'd
            # curl) — there is nothing to report and no socket left to
            # report it on; writing a 500 here would raise again and
            # spam handle_error tracebacks on every aborted scrape
            pass
        # sbt-lint: disable=swallowed-fault — surfaced to the scraper as a 500 body carrying the error
        except Exception as e:  # noqa: BLE001 — the instrument panel
            # must report its own faults, not close the connection
            try:
                self._send_json(500, {"error": repr(e)})
            except OSError:
                pass

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code: int, obj: dict) -> None:
        self._send(code, json.dumps(obj, default=str),
                   "application/json")

    def log_message(self, format: str, *args: Any) -> None:
        """Silence per-request stderr lines — scrapes every few seconds
        would otherwise drown the process's real logging."""


def start_server(
    port: int | None = None, host: str = "127.0.0.1",
) -> int:
    """Start the exposition server on a daemon thread; returns the
    bound port (useful with ``port=0``). Idempotent while running —
    a second call returns the live server's port. ``port=None`` reads
    ``SBT_METRICS_PORT``. Arms the default flight recorder so
    ``/debug/spans`` has an event window to serve."""
    global _server, _thread, _t_start
    from spark_bagging_tpu.telemetry import recorder

    with _module_lock:
        if _server is not None:
            return _server.server_address[1]
        if port is None:
            env = os.environ.get("SBT_METRICS_PORT", "")
            if not env:
                raise ValueError(
                    "no port given and SBT_METRICS_PORT is not set"
                )
            port = int(env)
        srv = ThreadingHTTPServer((host, int(port)), _Handler)
        srv.daemon_threads = True
        thread = threading.Thread(
            target=srv.serve_forever, kwargs={"poll_interval": 0.25},
            daemon=True, name="sbt-telemetry-server",
        )
        # start INSIDE the lock: a concurrent stop_server() that saw
        # the published globals would otherwise call srv.shutdown(),
        # which blocks forever unless serve_forever() is already
        # running (socketserver's __is_shut_down handshake)
        thread.start()
        _server, _thread, _t_start = srv, thread, time.monotonic()
    recorder.arm()
    return srv.server_address[1]


def stop_server() -> None:
    """Shut the server down and join its thread (idempotent). Leaves
    the flight recorder armed — failures after the scrape endpoint
    goes away are exactly the ones worth recording."""
    global _server, _thread, _t_start
    with _module_lock:
        srv, thread = _server, _thread
        _server = _thread = _t_start = None
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if thread is not None:
        thread.join(5.0)


def server_address() -> tuple[str, int] | None:
    """``(host, port)`` while running, else None."""
    with _module_lock:
        if _server is None:
            return None
        addr = _server.server_address
        return (str(addr[0]), int(addr[1]))


def maybe_start_from_env() -> int | None:
    """Start iff ``SBT_METRICS_PORT`` is set (the package calls this at
    import, making ``SBT_METRICS_PORT=9100 python serve.py`` the whole
    opt-in story). Never raises — a bad port or an occupied socket
    must not take down the workload it observes."""
    if not os.environ.get("SBT_METRICS_PORT", ""):
        return None
    try:
        return start_server()
    except Exception as e:  # noqa: BLE001 — observability is optional
        import warnings

        warnings.warn(
            f"SBT_METRICS_PORT is set but the telemetry server failed "
            f"to start: {e!r}",
            RuntimeWarning,
            stacklevel=2,
        )
        return None
