"""SLO specs and the replay regression gate.

A replay (``benchmarks/replay.py``) produces a metric report — latency
percentiles, throughput, padding waste, overload sheds, post-warmup
compile count. This module turns that report into a CI verdict two
ways:

- **absolute**: an :class:`SLOSpec` names hard ceilings/floors
  (p50/p95/p99 latency, rps floor, padding-waste ceiling, overload
  budget, zero post-warmup recompiles) and :func:`evaluate` checks the
  report against it;
- **relative**: :func:`compare_to_baseline` diffs the report against a
  previously saved one with tolerance bands (throughput may not drop
  more than ``rps_tolerance``, latency percentiles may not grow more
  than ``latency_tolerance``) — the "did this PR slow the hot path"
  gate ROADMAP item 3 demands, robust to host noise because the bands
  are wide and the failure they hunt (a 2x forward regression) is not.

Both return an :class:`SLOResult` whose ``checks`` list one verdict
per criterion; ``python -m benchmarks.replay --check`` renders it as a
JSON report and exits nonzero on any failed check.

Latency-percentile semantics: replay reports carry EXACT percentiles
(computed from the full per-request latency list the tracing plane
collected), not histogram interpolations — the gate compares real
order statistics.
"""

from __future__ import annotations

import json
from typing import Any

#: Tolerance bands for baseline comparison. Wide by design: CI hosts
#: are noisy and the regressions worth gating on (a 2x forward
#: slowdown) blow far past these.
DEFAULT_RPS_TOLERANCE = 0.35
DEFAULT_LATENCY_TOLERANCE = 0.75

#: The gate exit-code contract shared by ``benchmarks/replay.py
#: --check`` and ``benchmarks/scenarios`` (the ``serving_latency.py
#: --devices`` precedent, documented in benchmarks/BUDGETS.md):
#: 0 = every check green; 2 = a host-independent invariant broke
#: (digest mismatch, compile count, overload/shed budget, drift/
#: chaos/fleet transcript); 3 = ONLY host-conditional performance
#: bands failed (rps, latency percentiles, wall-clock stage shares) —
#: real on a sized host, expected noise on a loaded shared one, so CI
#: can treat 3 as a warning band without losing the hard gate.
EXIT_OK = 0
EXIT_BREACH = 2
EXIT_HOST_BAND = 3

#: check-name classification for the contract above: these prefixes
#: (matched against ``SLOResult.checks[*]["name"]``) are wall-clock
#: measurements a loaded host legitimately moves
HOST_BAND_CHECK_PREFIXES = ("rps", "latency_", "stage_share_")


def is_host_band_check(name: str) -> bool:
    """True when a failed check of this name is a host-conditional
    performance band (exit 3) rather than a hard breach (exit 2)."""
    return name.startswith(HOST_BAND_CHECK_PREFIXES)


def exit_code(result: "SLOResult") -> int:
    """Map a gate verdict to the shared exit-code contract.

    A failed band-named check whose measured value is MISSING
    (``actual is None`` — a broken/incomplete report, see ``_check``)
    is a hard breach, never host noise: the band exit exists for real
    measurements a loaded host legitimately moves, not for gates that
    measured nothing."""
    if result.ok:
        return EXIT_OK
    if all(is_host_band_check(c["name"]) and c.get("actual") is not None
           for c in result.failures):
        return EXIT_HOST_BAND
    return EXIT_BREACH


class SLOSpec:
    """Hard serving-SLO bounds. ``None`` disables a criterion.

    ``max_padding_waste`` bounds wasted work as a fraction: padding
    rows over total padded rows — or, when the replay report carries
    compiled-cost attribution (``sbt_serving_bucket_cost_*``), padding
    FLOPs over total FLOPs, the honest denominator.
    ``max_post_warmup_compiles`` defaults to 0 — the serving
    subsystem's founding contract.

    ``max_stage_share`` bounds per-stage attribution shares from the
    report's ``attribution`` section (``telemetry/perf.py``): a dict
    like ``{"queue": 0.5}`` fails the gate when queue wait exceeds
    half the measured request wall-clock — "slow because waiting" is
    a different regression than "slow because computing", and this is
    where a spec says which one it refuses to ship.
    """

    FIELDS = (
        "p50_ms", "p95_ms", "p99_ms", "min_rps", "max_padding_waste",
        "max_overloads", "max_post_warmup_compiles", "max_stage_share",
    )

    #: valid keys for ``max_stage_share`` (the perf plane's exact
    #: wall-clock decomposition)
    STAGES = ("queue", "forward", "scatter")

    def __init__(
        self,
        *,
        p50_ms: float | None = None,
        p95_ms: float | None = None,
        p99_ms: float | None = None,
        min_rps: float | None = None,
        max_padding_waste: float | None = None,
        max_overloads: int | None = None,
        max_post_warmup_compiles: int | None = 0,
        max_stage_share: dict[str, float] | None = None,
    ) -> None:
        self.p50_ms = p50_ms
        self.p95_ms = p95_ms
        self.p99_ms = p99_ms
        self.min_rps = min_rps
        self.max_padding_waste = max_padding_waste
        self.max_overloads = max_overloads
        self.max_post_warmup_compiles = max_post_warmup_compiles
        if max_stage_share is not None:
            unknown = set(max_stage_share) - set(self.STAGES)
            if unknown:
                raise ValueError(
                    f"unknown stages in max_stage_share: "
                    f"{sorted(unknown)}; have {list(self.STAGES)}"
                )
            for stage, limit in max_stage_share.items():
                if not 0.0 <= float(limit) <= 1.0:
                    raise ValueError(
                        f"max_stage_share[{stage!r}] must be in "
                        f"[0, 1], got {limit}"
                    )
        self.max_stage_share = max_stage_share

    def to_dict(self) -> dict[str, Any]:
        return {f: getattr(self, f) for f in self.FIELDS}

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "SLOSpec":
        unknown = set(d) - set(cls.FIELDS)
        if unknown:
            raise ValueError(
                f"unknown SLO spec fields {sorted(unknown)}; "
                f"have {list(cls.FIELDS)}"
            )
        return cls(**d)

    @classmethod
    def load(cls, path: str) -> "SLOSpec":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def __repr__(self) -> str:
        set_fields = {k: v for k, v in self.to_dict().items()
                      if v is not None}
        return f"SLOSpec({set_fields})"


class SLOResult:
    """Verdict of one evaluation: per-criterion checks + overall ok."""

    def __init__(self, checks: list[dict[str, Any]],
                 kind: str = "absolute") -> None:
        self.checks = checks
        self.kind = kind

    @property
    def ok(self) -> bool:
        return all(c["ok"] for c in self.checks)

    @property
    def failures(self) -> list[dict[str, Any]]:
        return [c for c in self.checks if not c["ok"]]

    def to_dict(self) -> dict[str, Any]:
        return {"kind": self.kind, "ok": self.ok, "checks": self.checks}

    def render(self) -> str:
        """Human one-line-per-check summary for the CLI."""
        lines = []
        for c in self.checks:
            mark = "PASS" if c["ok"] else "FAIL"
            lines.append(
                f"  [{mark}] {c['name']}: {c['actual']} "
                f"(limit {c['op']} {c['limit']})"
            )
        verdict = "OK" if self.ok else "SLO VIOLATION"
        return f"{verdict} ({self.kind})\n" + "\n".join(lines)


def _check(name: str, actual, limit, op: str) -> dict[str, Any]:
    if actual is None:
        # a spec bound with no measured value is a broken report, not
        # a pass — gate pipelines must fail loudly on missing data
        return {"name": name, "actual": None, "limit": limit,
                "op": op, "ok": False,
                "note": "report carries no value for this criterion"}
    ok = actual <= limit if op == "<=" else actual >= limit
    return {"name": name, "actual": actual, "limit": limit, "op": op,
            "ok": bool(ok)}


def evaluate(spec: SLOSpec, report: dict[str, Any]) -> SLOResult:
    """Check a replay report against hard SLO bounds.

    ``report`` is the dict ``benchmarks.replay.replay()`` returns
    (``latency_ms`` percentiles, ``rps``, ``padding`` fractions,
    ``overloads``, ``post_warmup_compiles``).
    """
    lat = report.get("latency_ms") or {}
    pad = report.get("padding") or {}
    checks: list[dict[str, Any]] = []
    for q in ("p50", "p95", "p99"):
        limit = getattr(spec, f"{q}_ms")
        if limit is not None:
            checks.append(_check(f"latency_{q}_ms", lat.get(q), limit, "<="))
    if spec.min_rps is not None:
        checks.append(_check("rps", report.get("rps"), spec.min_rps, ">="))
    if spec.max_padding_waste is not None:
        # prefer the FLOPs-weighted fraction when cost attribution ran
        waste = pad.get("waste_flops_frac")
        name = "padding_waste_flops_frac"
        if waste is None:
            waste = pad.get("waste_rows_frac")
            name = "padding_waste_rows_frac"
        checks.append(_check(name, waste, spec.max_padding_waste, "<="))
    if spec.max_overloads is not None:
        checks.append(_check("overloads", report.get("overloads"),
                             spec.max_overloads, "<="))
    if spec.max_post_warmup_compiles is not None:
        checks.append(_check(
            "post_warmup_compiles", report.get("post_warmup_compiles"),
            spec.max_post_warmup_compiles, "<=",
        ))
    if spec.max_stage_share:
        stages = (report.get("attribution") or {}).get("stages") or {}
        for stage in sorted(spec.max_stage_share):
            share = (stages.get(stage) or {}).get("share")
            checks.append(_check(
                f"stage_share_{stage}", share,
                spec.max_stage_share[stage], "<=",
            ))
    return SLOResult(checks, kind="absolute")


def compare_to_baseline(
    report: dict[str, Any],
    baseline: dict[str, Any],
    *,
    rps_tolerance: float = DEFAULT_RPS_TOLERANCE,
    latency_tolerance: float = DEFAULT_LATENCY_TOLERANCE,
) -> SLOResult:
    """Relative regression gate: the report may not be materially worse
    than the baseline report.

    Throughput floor: ``rps >= baseline_rps * (1 - rps_tolerance)``.
    Latency ceilings: each percentile ``<= baseline * (1 +
    latency_tolerance * tail factor)`` where the tail factor widens
    with the percentile (1x / 2x / 3x for p50 / p95 / p99): on a
    shared CI host the far tail of sub-millisecond batches is
    scheduler noise, while a real hot-path regression moves the median
    and throughput decisively — the gate leans on the stable signals
    and keeps the tails as wide tripwires. Determinism invariants are
    compared exactly: post-warmup compiles may not exceed the
    baseline's, and when both reports carry an ``output_digest`` over
    the same workload digest, they must match bitwise.
    """
    checks: list[dict[str, Any]] = []
    base_rps = baseline.get("rps")
    if base_rps:
        checks.append(_check(
            "rps_vs_baseline", report.get("rps"),
            round(base_rps * (1.0 - rps_tolerance), 3), ">=",
        ))
    base_lat = baseline.get("latency_ms") or {}
    lat = report.get("latency_ms") or {}
    for q, tail_factor in (("p50", 1.0), ("p95", 2.0), ("p99", 3.0)):
        b = base_lat.get(q)
        if b is not None:
            checks.append(_check(
                f"latency_{q}_vs_baseline", lat.get(q),
                round(b * (1.0 + latency_tolerance * tail_factor), 4),
                "<=",
            ))
    base_compiles = baseline.get("post_warmup_compiles")
    if base_compiles is not None:
        # suffixed like every other relative check: a combined
        # absolute+baseline gate would otherwise render two
        # identically-named compile checks with different limits
        checks.append(_check(
            "post_warmup_compiles_vs_baseline",
            report.get("post_warmup_compiles"), base_compiles, "<=",
        ))
    # bitwise determinism: same workload + same seed must reproduce the
    # baseline's outputs exactly — only comparable when both reports
    # ran the identical EXPERIMENT: same schedule (workload digest),
    # same payload seed (output bytes derive from it), same batcher
    # knobs (composition derives from them), and both in virtual mode
    # (timed mode is documented non-deterministic: its batch
    # composition follows a real worker clock, so differing output
    # bytes there are expected, not a breach)
    if (
        report.get("mode", "virtual") == "virtual"
        and baseline.get("mode", "virtual") == "virtual"
        and report.get("workload_digest") is not None
        and report.get("workload_digest") == baseline.get("workload_digest")
        and report.get("seed") == baseline.get("seed")
        and report.get("batcher") == baseline.get("batcher")
        and baseline.get("output_digest") is not None
    ):
        same = report.get("output_digest") == baseline["output_digest"]
        checks.append({
            "name": "output_digest_vs_baseline",
            "actual": report.get("output_digest"),
            "limit": baseline["output_digest"],
            "op": "==", "ok": bool(same),
        })
    return SLOResult(checks, kind="baseline")
