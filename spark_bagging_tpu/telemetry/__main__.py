"""CLI: ``python -m spark_bagging_tpu.telemetry dump [events.jsonl]``.

With no argument, dumps THIS process's registry in Prometheus text
format (useful from a REPL/notebook via ``%run``; a fresh process has
an empty registry). With a JSONL event-log path (written by
``telemetry.capture(path)``), reconstructs the log's final ``metrics``
snapshot and renders that — the offline way to turn a recorded run
into a scrape-able dump.
"""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m spark_bagging_tpu.telemetry", description=__doc__
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    dump = sub.add_parser(
        "dump", help="render metrics in Prometheus text format"
    )
    dump.add_argument(
        "jsonl", nargs="?", default=None,
        help="JSONL event log to render (default: this process's registry)",
    )
    args = p.parse_args(argv)

    from spark_bagging_tpu import telemetry

    if args.jsonl is None:
        sys.stdout.write(telemetry.render_prometheus())
        return 0
    events = telemetry.read_events(args.jsonl)
    snap = telemetry.last_metrics_snapshot(events)
    if snap is None:
        print(
            f"no metrics snapshot found in {args.jsonl!r} "
            "(was the capture closed?)", file=sys.stderr,
        )
        return 1
    sys.stdout.write(telemetry.render_prometheus(snap))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
