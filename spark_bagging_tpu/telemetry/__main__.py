"""CLI: ``python -m spark_bagging_tpu.telemetry dump|profile ...``.

With no argument, dumps THIS process's registry in Prometheus text
format (useful from a REPL/notebook via ``%run``; a fresh process has
an empty registry). With a JSONL event-log path (written by
``telemetry.capture(path)``), reconstructs the log's final ``metrics``
snapshot and renders that — the offline way to turn a recorded run
into a scrape-able dump.

``dump --merge a.jsonl b.jsonl ...`` merges SEVERAL per-process logs
into one fleet dump through the exact same merge the live
``FleetAggregator`` uses (``telemetry/fleet.py``): counters sum,
gauges keep per-process values under a ``process=`` label (derived
from each file's name) plus ``fleet=min/max/sum`` aggregates, and
histograms merge bucket-wise — so the dump's ``# quantiles`` lines
are computed from the union of the processes' bucket counts, never
from averaged percentiles.

Every histogram additionally gets a ``# quantiles`` comment line with
its p50/p95/p99 estimate (log-bucket interpolation) — comment lines
are legal in the exposition format, so the output stays scrape-
parseable while a human reading the dump gets the SLO trio for free
(``--no-quantiles`` drops them for byte-stable diffs).

``profile --seconds N [--port P | --url http://host:port]`` triggers
an on-demand live device profile on a RUNNING serving process through
its exposition server's ``/debug/profile`` route (the port defaults
to ``$SBT_METRICS_PORT``): the capture starts immediately, auto-stops
after N seconds (hard-capped server-side), and lands under the
process's ``telemetry_dir()/profiles/`` — no restart, no code change.
``profile --stop`` ends a running capture early. Exit 1 when the
process already has a capture running (HTTP 409 single-flight).
"""

from __future__ import annotations

import argparse
import os
import sys


def _quantile_comments(snapshot: list[dict]) -> str:
    from spark_bagging_tpu.telemetry.registry import snapshot_quantiles

    lines = []
    for entry in snapshot:
        if entry["kind"] != "histogram":
            continue
        qs = snapshot_quantiles(entry)
        labels = "".join(
            f",{k}={v}" for k, v in sorted(entry["labels"].items())
        )
        stats = " ".join(
            f"{k}={'nan' if v is None else format(v, '.6g')}"
            for k, v in qs.items()
        )
        lines.append(f"# quantiles {entry['name']}{labels} {stats}")
    return "\n".join(lines) + ("\n" if lines else "")


def _profile_cmd(p: argparse.ArgumentParser, args) -> int:
    """Drive a remote process's ``/debug/profile`` route (stdlib
    urllib — the CLI must work on an operator box with nothing but
    this package installed)."""
    import json
    import urllib.error
    import urllib.request

    base = args.url
    if base is None:
        port = args.port
        if port is None:
            env = os.environ.get("SBT_METRICS_PORT", "")
            if not env:
                p.error(
                    "no target: pass --port/--url or set "
                    "SBT_METRICS_PORT to the serving process's "
                    "exposition port"
                )
            port = int(env)
        base = f"http://127.0.0.1:{port}"
    if args.stop:
        url = f"{base.rstrip('/')}/debug/profile?action=stop"
    else:
        if args.seconds <= 0:
            p.error(f"--seconds must be > 0, got {args.seconds}")
        url = (f"{base.rstrip('/')}/debug/profile"
               f"?seconds={args.seconds}")
    try:
        with urllib.request.urlopen(url, timeout=10.0) as resp:
            body = json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read().decode("utf-8"))
        # sbt-lint: disable=swallowed-fault — the HTTPError itself is the payload: stringified into the body printed to stderr with exit 1 below
        except Exception:  # noqa: BLE001 — a non-JSON error body
            body = {"error": str(e)}
        print(json.dumps(body), file=sys.stderr)
        return 1
    except OSError as e:
        print(f"cannot reach {url!r}: {e}", file=sys.stderr)
        return 1
    print(json.dumps(body))
    if body.get("started"):
        print(
            f"profiling for {args.seconds}s into {body.get('dir')!r} "
            "(auto-stops; view with tensorboard/perfetto)",
            file=sys.stderr,
        )
    return 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m spark_bagging_tpu.telemetry", description=__doc__
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    dump = sub.add_parser(
        "dump", help="render metrics in Prometheus text format"
    )
    dump.add_argument(
        "jsonl", nargs="*", default=[],
        help="JSONL event log(s) to render (default: this process's "
             "registry; several only with --merge)",
    )
    dump.add_argument(
        "--merge", action="store_true",
        help="merge the per-process snapshots of SEVERAL event logs "
             "into one fleet dump (the FleetAggregator's exact merge: "
             "counters sum, gauges get process= labels + fleet "
             "min/max/sum, histograms merge bucket-wise)",
    )
    dump.add_argument(
        "--no-quantiles", action="store_true",
        help="omit the per-histogram `# quantiles` comment lines",
    )
    prof = sub.add_parser(
        "profile",
        help="trigger an on-demand live device profile on a running "
             "serving process via its /debug/profile route",
    )
    prof.add_argument(
        "--seconds", type=float, default=5.0,
        help="capture duration; the server auto-stops the profiler "
             "after this (clamped to its hard max)",
    )
    prof.add_argument(
        "--port", type=int, default=None,
        help="exposition-server port on localhost "
             "(default: $SBT_METRICS_PORT)",
    )
    prof.add_argument(
        "--url", default=None,
        help="full base URL of the exposition server "
             "(overrides --port)",
    )
    prof.add_argument(
        "--stop", action="store_true",
        help="stop the process's running capture instead of starting "
             "one",
    )
    args = p.parse_args(argv)

    if args.cmd == "profile":
        return _profile_cmd(p, args)

    from spark_bagging_tpu import telemetry

    def _read_snapshot(path: str):
        events = telemetry.read_events(path)
        snap = telemetry.last_metrics_snapshot(events)
        if snap is None:
            print(
                f"no metrics snapshot found in {path!r} "
                "(was the capture closed?)", file=sys.stderr,
            )
        return snap

    if args.merge:
        if not args.jsonl:
            p.error("--merge needs at least one JSONL event log")
        from spark_bagging_tpu.telemetry import fleet

        named = []
        seen: dict[str, int] = {}
        for path in args.jsonl:
            snap = _read_snapshot(path)
            if snap is None:
                return 1
            # process label from the file name; duplicates get a
            # #index suffix so two runs named telemetry.jsonl stay
            # distinguishable in the merged gauges
            base = os.path.basename(path)
            for suffix in (".workload.jsonl", ".jsonl"):
                if base.endswith(suffix):
                    base = base[: -len(suffix)]
                    break
            n = seen.get(base, 0)
            seen[base] = n + 1
            named.append((base if n == 0 else f"{base}#{n}", snap))
        snap, dropped = fleet.merge_snapshots(named)
        for name in dropped:
            print(
                f"dropped {name!r}: processes disagree on metric kind "
                "or histogram bounds (cannot merge exactly)",
                file=sys.stderr,
            )
    elif not args.jsonl:
        snap = telemetry.registry().snapshot()
    elif len(args.jsonl) > 1:
        p.error("several event logs need --merge")
    else:
        snap = _read_snapshot(args.jsonl[0])
        if snap is None:
            return 1
    sys.stdout.write(telemetry.render_prometheus(snap))
    if not args.no_quantiles:
        sys.stdout.write(_quantile_comments(snap))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
