"""CLI: ``python -m spark_bagging_tpu.telemetry dump [events.jsonl]``.

With no argument, dumps THIS process's registry in Prometheus text
format (useful from a REPL/notebook via ``%run``; a fresh process has
an empty registry). With a JSONL event-log path (written by
``telemetry.capture(path)``), reconstructs the log's final ``metrics``
snapshot and renders that — the offline way to turn a recorded run
into a scrape-able dump.

Every histogram additionally gets a ``# quantiles`` comment line with
its p50/p95/p99 estimate (log-bucket interpolation) — comment lines
are legal in the exposition format, so the output stays scrape-
parseable while a human reading the dump gets the SLO trio for free
(``--no-quantiles`` drops them for byte-stable diffs).
"""

from __future__ import annotations

import argparse
import sys


def _quantile_comments(snapshot: list[dict]) -> str:
    from spark_bagging_tpu.telemetry.registry import snapshot_quantiles

    lines = []
    for entry in snapshot:
        if entry["kind"] != "histogram":
            continue
        qs = snapshot_quantiles(entry)
        labels = "".join(
            f",{k}={v}" for k, v in sorted(entry["labels"].items())
        )
        stats = " ".join(
            f"{k}={'nan' if v is None else format(v, '.6g')}"
            for k, v in qs.items()
        )
        lines.append(f"# quantiles {entry['name']}{labels} {stats}")
    return "\n".join(lines) + ("\n" if lines else "")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m spark_bagging_tpu.telemetry", description=__doc__
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    dump = sub.add_parser(
        "dump", help="render metrics in Prometheus text format"
    )
    dump.add_argument(
        "jsonl", nargs="?", default=None,
        help="JSONL event log to render (default: this process's registry)",
    )
    dump.add_argument(
        "--no-quantiles", action="store_true",
        help="omit the per-histogram `# quantiles` comment lines",
    )
    args = p.parse_args(argv)

    from spark_bagging_tpu import telemetry

    if args.jsonl is None:
        snap = telemetry.registry().snapshot()
    else:
        events = telemetry.read_events(args.jsonl)
        snap = telemetry.last_metrics_snapshot(events)
        if snap is None:
            print(
                f"no metrics snapshot found in {args.jsonl!r} "
                "(was the capture closed?)", file=sys.stderr,
            )
            return 1
    sys.stdout.write(telemetry.render_prometheus(snap))
    if not args.no_quantiles:
        sys.stdout.write(_quantile_comments(snap))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
