"""CLI: ``python -m spark_bagging_tpu.telemetry dump [events.jsonl]``.

With no argument, dumps THIS process's registry in Prometheus text
format (useful from a REPL/notebook via ``%run``; a fresh process has
an empty registry). With a JSONL event-log path (written by
``telemetry.capture(path)``), reconstructs the log's final ``metrics``
snapshot and renders that — the offline way to turn a recorded run
into a scrape-able dump.

``dump --merge a.jsonl b.jsonl ...`` merges SEVERAL per-process logs
into one fleet dump through the exact same merge the live
``FleetAggregator`` uses (``telemetry/fleet.py``): counters sum,
gauges keep per-process values under a ``process=`` label (derived
from each file's name) plus ``fleet=min/max/sum`` aggregates, and
histograms merge bucket-wise — so the dump's ``# quantiles`` lines
are computed from the union of the processes' bucket counts, never
from averaged percentiles.

Every histogram additionally gets a ``# quantiles`` comment line with
its p50/p95/p99 estimate (log-bucket interpolation) — comment lines
are legal in the exposition format, so the output stays scrape-
parseable while a human reading the dump gets the SLO trio for free
(``--no-quantiles`` drops them for byte-stable diffs).
"""

from __future__ import annotations

import argparse
import os
import sys


def _quantile_comments(snapshot: list[dict]) -> str:
    from spark_bagging_tpu.telemetry.registry import snapshot_quantiles

    lines = []
    for entry in snapshot:
        if entry["kind"] != "histogram":
            continue
        qs = snapshot_quantiles(entry)
        labels = "".join(
            f",{k}={v}" for k, v in sorted(entry["labels"].items())
        )
        stats = " ".join(
            f"{k}={'nan' if v is None else format(v, '.6g')}"
            for k, v in qs.items()
        )
        lines.append(f"# quantiles {entry['name']}{labels} {stats}")
    return "\n".join(lines) + ("\n" if lines else "")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m spark_bagging_tpu.telemetry", description=__doc__
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    dump = sub.add_parser(
        "dump", help="render metrics in Prometheus text format"
    )
    dump.add_argument(
        "jsonl", nargs="*", default=[],
        help="JSONL event log(s) to render (default: this process's "
             "registry; several only with --merge)",
    )
    dump.add_argument(
        "--merge", action="store_true",
        help="merge the per-process snapshots of SEVERAL event logs "
             "into one fleet dump (the FleetAggregator's exact merge: "
             "counters sum, gauges get process= labels + fleet "
             "min/max/sum, histograms merge bucket-wise)",
    )
    dump.add_argument(
        "--no-quantiles", action="store_true",
        help="omit the per-histogram `# quantiles` comment lines",
    )
    args = p.parse_args(argv)

    from spark_bagging_tpu import telemetry

    def _read_snapshot(path: str):
        events = telemetry.read_events(path)
        snap = telemetry.last_metrics_snapshot(events)
        if snap is None:
            print(
                f"no metrics snapshot found in {path!r} "
                "(was the capture closed?)", file=sys.stderr,
            )
        return snap

    if args.merge:
        if not args.jsonl:
            p.error("--merge needs at least one JSONL event log")
        from spark_bagging_tpu.telemetry import fleet

        named = []
        seen: dict[str, int] = {}
        for path in args.jsonl:
            snap = _read_snapshot(path)
            if snap is None:
                return 1
            # process label from the file name; duplicates get a
            # #index suffix so two runs named telemetry.jsonl stay
            # distinguishable in the merged gauges
            base = os.path.basename(path)
            for suffix in (".workload.jsonl", ".jsonl"):
                if base.endswith(suffix):
                    base = base[: -len(suffix)]
                    break
            n = seen.get(base, 0)
            seen[base] = n + 1
            named.append((base if n == 0 else f"{base}#{n}", snap))
        snap, dropped = fleet.merge_snapshots(named)
        for name in dropped:
            print(
                f"dropped {name!r}: processes disagree on metric kind "
                "or histogram bounds (cannot merge exactly)",
                file=sys.stderr,
            )
    elif not args.jsonl:
        snap = telemetry.registry().snapshot()
    elif len(args.jsonl) > 1:
        p.error("several event logs need --merge")
    else:
        snap = _read_snapshot(args.jsonl[0])
        if snap is None:
            return 1
    sys.stdout.write(telemetry.render_prometheus(snap))
    if not args.no_quantiles:
        sys.stdout.write(_quantile_comments(snap))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
