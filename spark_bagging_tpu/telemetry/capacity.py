"""Capacity & residency observability plane [ISSUE 16, ROADMAP item 2].

One process hosting many model versions needs an exact answer to three
questions before any residency policy can exist: what does each
resident model COST (bytes held — params, compiled executables, AOT
disk), what demand JUSTIFIES that cost (per-model request/row rates,
popularity ranks, a hot/warm/cold classification with hysteresis), and
when the program cache evicts, WHOSE bytes went (owner-attributed
eviction accounting plus a decision explainer). This module is that
measurement plane — policy-free by design: it measures the inputs a
future admission/eviction policy will consume, it decides nothing.

Structure mirrors the other planes (``telemetry/perf.py``,
``faults.py``): a process-global ``ACTIVE`` attribute that serving hot
paths read ONCE per packed batch (the zero-overhead-unarmed contract,
micro-benchmarked in tier-1), ``enable()``/``disable()`` for users and
``install()`` as the replay harness's save/restore seam.

Measurement honesty rules:

- executable bytes walk a ladder — ``compiled.memory_analysis()``
  (code + temp) where the backend reports real sizes, serialized
  executable length as the fallback (CPU XLA reports 0 code bytes),
  and an explicit ``(None, "unmeasured")`` bottom. An unmeasured entry
  is surfaced as a flag, never counted as 0 bytes of residency.
- ledger sums RECONCILE: grouping the program cache's resident entries
  by owner (plus an ``"(unattributed)"`` bucket for fingerprints no
  registry commit ever claimed) must sum back to the cache's own
  totals, entry-for-entry and byte-for-byte — asserted in tier-1.
- ownership is established only at registry COMMIT (register/swap
  success). Cache entries are attributed lazily, at read time, by
  resolving their key's fingerprint through the plane: a failed swap's
  pre-commit compiles therefore never produce ledger entries (its
  fingerprint was never registered), while a successful swap's
  pre-commit warm compiles become attributed retroactively.
"""

from __future__ import annotations

import collections
import time
from typing import Any

from spark_bagging_tpu import telemetry
from spark_bagging_tpu.analysis.locks import make_lock

#: demand classes, hottest first; exported numerically on the
#: ``sbt_capacity_demand_class`` gauge (2=hot, 1=warm, 0=cold)
CLASSES = ("hot", "warm", "cold")
CLASS_LEVEL = {"hot": 2.0, "warm": 1.0, "cold": 0.0}

#: rollup owner for cache entries whose fingerprint no registry commit
#: ever claimed (anonymous executors, failed swaps' pre-commit builds)
UNATTRIBUTED = "(unattributed)"


# -- measurement ladder ------------------------------------------------

def executable_bytes(compiled: Any) -> tuple[int | None, str]:
    """Bytes held by a compiled executable, with the source of truth:
    ``(n, "memory_analysis")`` when the backend reports real code+temp
    sizes, ``(n, "serialized")`` from the serialized executable length
    otherwise (CPU XLA reports 0 code bytes), ``(None, "unmeasured")``
    when neither path works — honest None, never a made-up 0."""
    try:
        ma = compiled.memory_analysis()
        n = (int(getattr(ma, "generated_code_size_in_bytes", 0) or 0)
             + int(getattr(ma, "temp_size_in_bytes", 0) or 0))
        if n > 0:
            return n, "memory_analysis"
    except Exception:  # sbt-lint: disable=swallowed-fault — ladder falls through to the next measurement rung by contract
        pass
    try:
        from jax.experimental import serialize_executable

        payload, _, _ = serialize_executable.serialize(compiled)
        return len(payload), "serialized"
    except Exception:  # sbt-lint: disable=swallowed-fault — unmeasured is the ladder's explicit, surfaced bottom
        return None, "unmeasured"


def params_nbytes(executor: Any) -> int:
    """Bytes held by the executor's stacked param pytree (params +
    subspace index arrays) — exact leaf ``nbytes`` sums."""
    import jax

    total = 0
    for tree in (getattr(executor, "_params", None),
                 getattr(executor, "_subspaces", None)):
        if tree is None:
            continue
        for leaf in jax.tree_util.tree_leaves(tree):
            nb = getattr(leaf, "nbytes", None)
            if nb is None:
                try:
                    nb = leaf.size * leaf.dtype.itemsize
                except Exception:  # sbt-lint: disable=swallowed-fault — non-array leaf holds no accountable bytes
                    nb = 0
            total += int(nb)
    return total


def params_placement(executor: Any) -> str:
    """Where the param leaves live: the first leaf's device platform
    (``"cpu"``/``"tpu"``/``"gpu"``) or ``"host"`` for plain ndarrays."""
    import jax

    for tree in (getattr(executor, "_params", None),
                 getattr(executor, "_subspaces", None)):
        if tree is None:
            continue
        for leaf in jax.tree_util.tree_leaves(tree):
            devices = getattr(leaf, "devices", None)
            if callable(devices):
                try:
                    ds = devices()
                    if ds:
                        return next(iter(ds)).platform
                except Exception:  # sbt-lint: disable=swallowed-fault — placement is advisory; "host" is the honest fallback
                    pass
            return "host"
    return "host"


# -- demand classification ---------------------------------------------

def classify_rate(
    prev: str | None,
    rate_rps: float,
    *,
    hot_rps: float,
    warm_rps: float,
    hysteresis: float = 0.5,
) -> str:
    """Hot/warm/cold with hysteresis: a model classified hot (warm)
    stays there until its rate falls below ``hysteresis`` × the
    threshold that admitted it — so a model oscillating around a
    boundary does not flap the class gauge (and any policy reading it)
    every window. Pure: (previous class, rate) → class."""
    if rate_rps >= hot_rps:
        return "hot"
    if prev == "hot" and rate_rps >= hot_rps * hysteresis:
        return "hot"
    if rate_rps >= warm_rps:
        return "warm"
    if prev in ("hot", "warm") and rate_rps >= warm_rps * hysteresis:
        return "warm"
    return "cold"


# -- the plane ---------------------------------------------------------

# sbt-lint: shared-state
class CapacityPlane:
    """Per-(model, version) residency ledger + fixed-memory demand
    accumulators + owner-attributed eviction ring.

    Fed from three seams: registry commits (``register_owner`` — the
    ONLY place fingerprints acquire owners), the executor's packed
    forward (``observe_demand``, behind the one-attribute-read probe),
    and program-cache evictions (``observe_eviction``). All reads that
    join against the program cache (``ledger``/``report``) snapshot
    the cache FIRST, then take the plane lock — the two locks are
    never held together, in either order.
    """

    def __init__(
        self,
        *,
        max_models: int = 256,
        hot_rps: float = 50.0,
        warm_rps: float = 1.0,
        hysteresis: float = 0.5,
        max_eviction_events: int = 128,
    ) -> None:
        self.max_models = int(max_models)
        self.hot_rps = float(hot_rps)
        self.warm_rps = float(warm_rps)
        self.hysteresis = float(hysteresis)
        self._lock = make_lock("telemetry.capacity")
        #: fingerprint -> {"model", "version", "live"} — written only
        #: at registry commit; the lazy-attribution join key
        self._owners: dict[str, dict[str, Any]] = {}
        #: (model, version) -> residency facts known at commit time
        self._ledger: dict[tuple[str, int], dict[str, Any]] = {}
        #: model -> demand accumulators (fixed memory: max_models cap)
        self._demand: dict[str, dict[str, Any]] = {}
        self._demand_dropped = 0
        #: owner label -> cumulative evictions charged to it
        self._evicted_by: dict[str, int] = {}
        self._eviction_events: collections.deque = collections.deque(
            maxlen=int(max_eviction_events)
        )

    # -- ownership (registry commit seam) ------------------------------

    def register_owner(
        self,
        executor: Any,
        *,
        retired_fingerprint: str | None = None,
    ) -> None:
        """Record a COMMITTED (model, version): called by the registry
        after ``register``/``swap`` succeed, never from their failure
        paths — which is the whole no-leak contract: a replacement that
        never went live never acquires an owner mapping, so its cache
        entries roll up as unattributed instead of leaking ledger rows.

        ``retired_fingerprint``: on swap, the outgoing executor's
        fingerprint — its mapping stays (old entries remain attributed
        for eviction accounting) but is marked not-live.
        """
        model = executor.model_name
        version = int(executor.model_version)
        fingerprint = executor.fingerprint
        pbytes = params_nbytes(executor)
        placement = params_placement(executor)
        with self._lock:
            if retired_fingerprint and retired_fingerprint != fingerprint:
                prev = self._owners.get(retired_fingerprint)
                if prev is not None:
                    prev["live"] = False
                    key = (prev["model"], prev["version"])
                    if key in self._ledger:
                        self._ledger[key]["live"] = False
            self._owners[fingerprint] = {
                "model": model, "version": version, "live": True,
            }
            self._ledger[(model, version)] = {
                "fingerprint": fingerprint,
                "params_bytes": pbytes,
                "placement": placement,
                "aot_disk_bytes": None,
                "live": True,
            }
            n_models = len({m for m, _ in self._ledger})
        telemetry.set_gauge(
            "sbt_capacity_params_bytes", float(pbytes),
            labels={"model": model, "version": str(version)},
        )
        telemetry.set_gauge("sbt_capacity_models", float(n_models))

    def owner_label(self, fingerprint: str) -> str | None:
        """The committed model name for ``fingerprint``, or None —
        the lazy-attribution lookup the program cache labels with."""
        with self._lock:
            rec = self._owners.get(fingerprint)
            return None if rec is None else rec["model"]

    def owner_of(self, fingerprint: str) -> dict[str, Any] | None:
        with self._lock:
            rec = self._owners.get(fingerprint)
            return None if rec is None else dict(rec)

    def set_aot_bytes(self, model: str, version: int, nbytes: int) -> None:
        """AOT-cache disk bytes for a committed (model, version) —
        fed by ``aot_cache.save_executables``."""
        with self._lock:
            entry = self._ledger.get((model, int(version)))
            if entry is not None:
                entry["aot_disk_bytes"] = int(nbytes)
        telemetry.set_gauge("sbt_capacity_aot_disk_bytes", float(nbytes),
                            labels={"model": model})

    # -- demand (hot-path seam) ----------------------------------------

    def observe_demand(self, model: str, version: int | None,
                       requests: int, rows: int) -> None:
        """Accumulate one packed batch's demand against ``model``.
        Fixed memory: at most ``max_models`` tracked models; overflow
        is counted (``sbt_capacity_demand_dropped_total``), not grown.
        Called from ``_forward_packed`` under BOTH dispatch paths (the
        coalescing worker and the direct-dispatch inline serve), only
        when the plane is armed."""
        with self._lock:
            d = self._demand.get(model)
            if d is None:
                if len(self._demand) >= self.max_models:
                    self._demand_dropped += 1
                    d = None
                else:
                    d = {
                        "requests": 0, "rows": 0, "version": version,
                        "last_requests": 0, "last_now": None,
                        "rate_rps": 0.0, "class": "cold",
                    }
                    self._demand[model] = d
            if d is not None:
                d["requests"] += int(requests)
                d["rows"] += int(rows)
                d["version"] = version
        if d is None:
            telemetry.inc("sbt_capacity_demand_dropped_total")
            return
        labels = {"model": model}
        telemetry.inc("sbt_capacity_demand_requests_total",
                      float(requests), labels=labels)
        telemetry.inc("sbt_capacity_demand_rows_total",
                      float(rows), labels=labels)

    def classify(self, now: float | None = None) -> dict[str, dict]:
        """Advance one classification window: per-model interval rate
        since the last call, hysteresis class step, popularity rank
        (by cumulative requests, name tie-break). ``now`` is an
        injectable clock — wall by default, the virtual workload clock
        in the churn drill (which makes classes a pure function of the
        workload). Returns {model: {requests, rows, rate_rps, class,
        rank}} and exports the demand gauges."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            for d in self._demand.values():
                last = d["last_now"]
                if last is None:
                    # first window: no interval yet — rate stays 0
                    d["last_now"] = now
                    d["last_requests"] = d["requests"]
                    continue
                dt = now - last
                if dt <= 0:
                    continue
                rate = (d["requests"] - d["last_requests"]) / dt
                d["rate_rps"] = rate
                d["class"] = classify_rate(
                    d["class"], rate, hot_rps=self.hot_rps,
                    warm_rps=self.warm_rps, hysteresis=self.hysteresis,
                )
                d["last_now"] = now
                d["last_requests"] = d["requests"]
            out = self._demand_view_locked()
        for model, d in out.items():
            labels = {"model": model}
            telemetry.set_gauge("sbt_capacity_demand_rate_rps",
                                d["rate_rps"], labels=labels)
            telemetry.set_gauge("sbt_capacity_demand_rank",
                                float(d["rank"]), labels=labels)
            telemetry.set_gauge("sbt_capacity_demand_class",
                                CLASS_LEVEL[d["class"]], labels=labels)
        return out

    def _demand_view_locked(self) -> dict[str, dict]:
        """Ranked copy of the demand table; caller holds the lock."""
        order = sorted(self._demand,
                       key=lambda m: (-self._demand[m]["requests"], m))
        out = {}
        for rank, model in enumerate(order, start=1):
            d = self._demand[model]
            out[model] = {
                "requests": d["requests"], "rows": d["rows"],
                "rate_rps": d["rate_rps"], "class": d["class"],
                "rank": rank,
            }
        return out

    def demand_summary(self) -> dict[str, dict]:
        """Deterministic demand view (cumulative counts + rank +
        class, no clocks) — the churn transcript's demand section."""
        with self._lock:
            view = self._demand_view_locked()
        return {
            m: {"requests": d["requests"], "rows": d["rows"],
                "rank": d["rank"], "class": d["class"]}
            for m, d in view.items()
        }

    def demand_class(self, model: str) -> str:
        with self._lock:
            d = self._demand.get(model)
            return "cold" if d is None else d["class"]

    # -- eviction attribution (program-cache seam) ---------------------

    def observe_eviction(self, *, fingerprint: str, bucket: int,
                         variant: str, nbytes: int | None,
                         seq: int) -> str:
        """Charge one program-cache eviction to its owner (or the
        unattributed rollup). Returns the owner label so the cache can
        emit the model-labeled eviction counter without a second
        lookup. ``seq`` is the cache's monotonic insert sequence — the
        workload-pure event clock the churn transcript records."""
        with self._lock:
            rec = self._owners.get(fingerprint)
            label = UNATTRIBUTED if rec is None else rec["model"]
            self._evicted_by[label] = self._evicted_by.get(label, 0) + 1
            self._eviction_events.append({
                "owner": label, "bucket": int(bucket),
                "variant": variant, "bytes": nbytes, "seq": int(seq),
            })
        return label

    def eviction_counts(self) -> dict[str, int]:
        """Cumulative evictions charged per owner, name-sorted —
        deterministic, so the churn transcript can carry it."""
        with self._lock:
            return {k: self._evicted_by[k]
                    for k in sorted(self._evicted_by)}

    def recent_evictions(self, limit: int = 32) -> list[dict]:
        with self._lock:
            events = list(self._eviction_events)
        return [dict(e) for e in events[-int(limit):]]

    # -- ledger + explainer (joins against the program cache) ----------

    def ledger(self) -> dict[str, Any]:
        """The reconciliation surface: the installed program cache's
        resident entries grouped by owner, joined with commit-time
        residency facts. ``reconciled`` asserts the grouping sums back
        to the cache's own totals — entries, measured bytes, and
        unmeasured counts all conserved."""
        from spark_bagging_tpu.serving import program_cache as _pc

        snap = _pc.cache().snapshot()
        owners: dict[str, dict[str, Any]] = {}
        for e in snap["entries"]:
            label = self.owner_label(e["fingerprint"]) or UNATTRIBUTED
            o = owners.setdefault(label, {
                "entries": 0, "bytes": 0, "unmeasured": 0,
            })
            o["entries"] += 1
            if e["bytes"] is None:
                o["unmeasured"] += 1
            else:
                o["bytes"] += e["bytes"]
        with self._lock:
            committed = {
                f"{m}@{v}": {
                    "params_bytes": rec["params_bytes"],
                    "placement": rec["placement"],
                    "aot_disk_bytes": rec["aot_disk_bytes"],
                    "live": rec["live"],
                    "fingerprint": rec["fingerprint"],
                }
                for (m, v), rec in self._ledger.items()
            }
        reconciled = (
            sum(o["entries"] for o in owners.values()) == snap["entries_total"]
            and sum(o["bytes"] for o in owners.values()) == snap["bytes_total"]
            and sum(o["unmeasured"] for o in owners.values())
            == snap["unmeasured_total"]
        )
        for label, o in owners.items():
            if label != UNATTRIBUTED:
                telemetry.set_gauge("sbt_capacity_compiled_bytes",
                                    float(o["bytes"]),
                                    labels={"model": label})
                telemetry.set_gauge("sbt_capacity_resident_entries",
                                    float(o["entries"]),
                                    labels={"model": label})
                telemetry.set_gauge("sbt_capacity_unmeasured_entries",
                                    float(o["unmeasured"]),
                                    labels={"model": label})
        return {
            "cache": {
                "entries": snap["entries_total"],
                "capacity": snap["capacity"],
                "bytes": snap["bytes_total"],
                "unmeasured": snap["unmeasured_total"],
            },
            "owners": {k: owners[k] for k in sorted(owners)},
            "committed": committed,
            "reconciled": reconciled,
        }

    def export_gauges(self) -> None:
        """Refresh the policy-input gauges the alert rules read:
        cache headroom ratio and cold-but-resident entry count. Called
        on scrape (``telemetry/server.py``) and from ``report``."""
        led = self.ledger()
        cache = led["cache"]
        cap = cache["capacity"] or 1
        headroom = max(0.0, (cap - cache["entries"]) / cap)
        cold = 0
        for label, o in led["owners"].items():
            if label == UNATTRIBUTED:
                continue
            if self.demand_class(label) == "cold":
                cold += o["entries"]
        telemetry.set_gauge("sbt_capacity_cache_headroom_ratio", headroom)
        telemetry.set_gauge("sbt_capacity_cold_resident_entries",
                            float(cold))

    def report(self, *, limit: int = 64) -> dict[str, Any]:
        """The ``/debug/capacity`` body: ledger + per-resident
        eviction-decision explainer (LRU-first — position 0 is next to
        evict) + demand table + recent evictions + device memory.
        Every explainer row carries the exact inputs a residency
        policy would weigh: LRU position, demand rank/class, bytes
        reclaimable (None when unmeasured), last-hit age."""
        from spark_bagging_tpu.serving import program_cache as _pc
        from spark_bagging_tpu.utils.memory import device_memory_stats

        snap = _pc.cache().snapshot()
        led = self.ledger()
        demand = self.demand_summary()
        now = time.time()
        residents = []
        for e in snap["entries"][:int(limit)]:
            owner = self.owner_of(e["fingerprint"])
            label = UNATTRIBUTED if owner is None else owner["model"]
            d = demand.get(label)
            last_hit = e["ts_last_hit"]
            residents.append({
                "owner": label,
                "version": None if owner is None else owner["version"],
                "live": None if owner is None else owner["live"],
                "bucket": e["bucket"],
                "variant": e["variant"],
                "lru_position": e["lru_position"],
                "bytes_reclaimable": e["bytes"],
                "bytes_source": e["source"],
                "unmeasured": e["bytes"] is None,
                "hits": e["hits"],
                "last_hit_age_s": (None if last_hit is None
                                   else max(0.0, now - last_hit)),
                "demand_rank": None if d is None else d["rank"],
                "demand_class": "cold" if d is None else d["class"],
            })
        self.export_gauges()
        with self._lock:
            dropped = self._demand_dropped
        return {
            "enabled": True,
            "thresholds": {
                "hot_rps": self.hot_rps, "warm_rps": self.warm_rps,
                "hysteresis": self.hysteresis,
            },
            "cache": led["cache"],
            "owners": led["owners"],
            "committed": led["committed"],
            "reconciled": led["reconciled"],
            "residents": residents,
            "demand": demand,
            "demand_dropped": dropped,
            "evictions_by_owner": self.eviction_counts(),
            "evictions_recent": self.recent_evictions(),
            "device_memory": device_memory_stats(),
        }


def capacity_report(*, limit: int = 64) -> dict[str, Any]:
    """Route-friendly report: the armed plane's full explainer, or an
    honest disabled stub that still shows the cache totals."""
    plane = ACTIVE
    if plane is None:
        from spark_bagging_tpu.serving import program_cache as _pc

        return {
            "enabled": False,
            "cache": _pc.cache().stats(),
            "note": ("capacity plane not armed — "
                     "telemetry.capacity.enable() to attribute"),
        }
    return plane.report(limit=limit)


# -- process default ---------------------------------------------------

#: the probe target: serving hot paths read this ONE module attribute
#: (the ``faults.ACTIVE`` pattern) — None means the plane is off and
#: the probe cost is a single attribute read
ACTIVE: "CapacityPlane | None" = None

_default_lock = make_lock("telemetry.capacity.default")


def enable(**kwargs: Any) -> CapacityPlane:
    """Install a fresh :class:`CapacityPlane` as the process plane
    (``kwargs`` are its constructor options). A second enable starts a
    new accounting window — the old plane's state stays readable but
    is no longer fed."""
    global ACTIVE
    plane = CapacityPlane(**kwargs)
    with _default_lock:
        ACTIVE = plane
    return plane


def disable() -> None:
    """Uninstall the process plane (probes go back to one attribute
    read; accumulated state on the old plane stays readable)."""
    global ACTIVE
    with _default_lock:
        ACTIVE = None


def install(plane: "CapacityPlane | None") -> "CapacityPlane | None":
    """Install ``plane`` (or None) as the probe target, returning the
    previous one — the replay harness's save/restore seam."""
    global ACTIVE
    with _default_lock:
        prev = ACTIVE
        ACTIVE = plane
    return prev


def get() -> "CapacityPlane | None":
    """The installed plane, or None."""
    return ACTIVE
