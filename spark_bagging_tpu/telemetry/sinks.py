"""Event sinks: the per-run JSONL event log and the run registry.

A **run** is one observed window (typically one ``fit``/bench
invocation) opened with :func:`capture`. While open, every span event
and metric flush is delivered to the run's sink; the sink keeps the
events in memory (``run.events``) and, when a path was given, appends
them to a JSONL file — one JSON object per line, ``schema``-versioned
so downstream tooling can evolve the format without guessing.

Event kinds (all carry ``schema``/``run``/``ts``):

- ``run_start`` / ``run_end`` — window boundaries; ``run_end`` carries
  the wall-clock of the window.
- ``span`` — one completed phase span (``name``, ``path``, ``seconds``,
  ``sync``, optional ``attrs``).
- ``metrics`` — a full registry snapshot (flushed at ``run_end``, and
  on demand via ``Run.flush_metrics()``), the machine-readable
  instrument panel BENCH trajectories diff against.

The process-level **run registry** (:func:`runs`, :func:`current_run`)
lists every run opened in this process so late readers (a REPL, an
exception handler) can correlate events with the run that produced
them.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Iterator

SCHEMA_VERSION = 1


def telemetry_dir() -> str:
    """The directory run artifacts (JSONL event logs, flight-recorder
    dumps) land in: ``$SBT_TELEMETRY_DIR`` when set, else
    ``./telemetry/`` under the current working directory. Created on
    first use — artifacts are working state, not source, and live
    outside version control (``.gitignore`` covers the default)."""
    path = os.environ.get("SBT_TELEMETRY_DIR") or os.path.join(
        os.getcwd(), "telemetry"
    )
    os.makedirs(path, exist_ok=True)
    return path


def default_log_path(name: str = "telemetry.jsonl") -> str:
    """``name`` resolved inside :func:`telemetry_dir` — what bench.py
    and the serving benchmark pass to :func:`capture` by default."""
    return os.path.join(telemetry_dir(), name)


_run_seq = itertools.count(1)
_runs_lock = threading.Lock()
_runs: list["Run"] = []


# With a file sink attached, the in-memory mirror keeps only this many
# events — a multi-epoch out-of-core stream emits one span per chunk,
# and duplicating millions of event dicts on the host would OOM exactly
# the workloads the streaming engine exists for. The JSONL file stays
# complete; `n_events` counts everything.
MAX_MIRRORED_EVENTS = 10_000


class Run:
    """One capture window: in-memory event list + optional JSONL file.

    ``events`` mirrors the stream in memory, capped at
    ``MAX_MIRRORED_EVENTS`` when a file sink is attached (the file gets
    every event; ``n_events`` is the true total). File-less captures
    keep everything — they ARE the sink.
    """

    def __init__(self, path: str | None, label: str | None) -> None:
        self.run_id = f"run-{os.getpid()}-{next(_run_seq)}"
        self.label = label
        self.path = path
        self.events: list[dict] = []
        self.n_events = 0
        self.t_start = time.time()
        self._lock = threading.Lock()
        self._file = open(path, "a", buffering=1) if path else None

    def emit(self, event: dict) -> None:
        event = {
            "schema": SCHEMA_VERSION,
            "run": self.run_id,
            **event,
        }
        event.setdefault("ts", time.time())
        with self._lock:
            self.n_events += 1
            if (self._file is None
                    or len(self.events) < MAX_MIRRORED_EVENTS):
                self.events.append(event)
            if self._file is not None:
                json.dump(event, self._file, default=str)
                self._file.write("\n")

    def flush_metrics(self) -> None:
        """Append a full registry snapshot as one ``metrics`` event."""
        from spark_bagging_tpu.telemetry.state import STATE

        self.emit({"kind": "metrics", "metrics": STATE.registry.snapshot()})

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def spans(self, name: str | None = None) -> list[dict]:
        """Recorded span events, optionally filtered by name."""
        return [
            e for e in self.events
            if e["kind"] == "span" and (name is None or e["name"] == name)
        ]


def runs() -> list[Run]:
    """Every run opened in this process, in open order."""
    with _runs_lock:
        return list(_runs)


_active: list[Run] = []


def current_run() -> Run | None:
    """The innermost open capture, or None."""
    with _runs_lock:
        return _active[-1] if _active else None


def capture_open() -> bool:
    """True while any ``capture()`` window is open. Lock-free read of
    the active list's truthiness — this sits on per-request gates
    (serving arrival events), where a benign race beats a lock."""
    return bool(_active)


@contextmanager
def capture(
    path: str | None = None,
    *,
    label: str | None = None,
    device_sync: bool | None = None,
) -> Iterator[Run]:
    """Open a telemetry run: events recorded while the block runs are
    collected on the returned :class:`Run` (and appended to ``path``
    as JSONL when given — APPENDED, so one file can accumulate many
    runs, distinguished by their ``run`` ids; unlink it first for a
    fresh log, as bench.py does). Captures nest; each event goes to
    every open capture. Opening a capture force-enables telemetry for
    its duration (an explicit observation request beats the ambient
    switch); ``device_sync`` optionally opts span timing into device
    barriers for the window.
    """
    from spark_bagging_tpu.telemetry.state import STATE

    run = Run(path, label)
    prev_enabled = STATE.enabled
    prev_sync = STATE.device_sync
    STATE.enabled = True
    if device_sync is not None:
        STATE.device_sync = device_sync
    with _runs_lock:
        _runs.append(run)
        _active.append(run)
    STATE.add_sink(run)
    run.emit({"kind": "run_start", "label": label})
    try:
        yield run
    finally:
        run.flush_metrics()
        run.emit({
            "kind": "run_end",
            "seconds": time.time() - run.t_start,
            "n_events": run.n_events + 1,
        })
        STATE.remove_sink(run)
        with _runs_lock:
            if run in _active:
                _active.remove(run)
        STATE.enabled = prev_enabled
        STATE.device_sync = prev_sync
        run.close()


def read_events(path: str) -> list[dict]:
    """Parse a JSONL event log back into event dicts (blank lines
    skipped; raises on malformed lines — a torn log should be loud)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def last_metrics_snapshot(events: list[dict]) -> list[dict] | None:
    """The final registry snapshot recorded in an event list, or None."""
    for e in reversed(events):
        if e.get("kind") == "metrics":
            return e["metrics"]
    return None
