"""Per-request distributed tracing — identity for the serving path.

A slow served request is unexplainable without attribution: did it sit
in the micro-batcher queue, wait for batch-mates, or pay the device
forward? This module mints the identity that threads the whole path:

- every ``MicroBatcher.submit()`` creates a :class:`TraceContext` — a
  ``trace_id`` (one request's journey) plus a human-pasteable
  ``request_id`` — exposed on the returned future as ``future.trace``;
- spans opened while a context is *installed* on the thread
  (:func:`use`) carry ``trace_id``/``span_id``/``parent_id`` in their
  event dicts, so the JSONL log and the ``/debug/spans`` ring become a
  queryable span tree;
- the batcher worker installs a **batch context** whose ``links`` list
  the member requests' trace ids: the coalesced ``serving_batch`` /
  ``serving_forward`` / ``serving_scatter`` spans belong to one batch
  but are resolvable from every request riding it (the one-to-many
  fan-in that makes micro-batched tracing different from RPC tracing);
- :func:`annotate` lets deep layers (the executor's bucket choice)
  attach facts to whatever context is current without plumbing
  arguments through every call signature.

Cost contract: when telemetry is disabled no context is ever minted
(``future.trace is None``); when no context is installed the span-path
hook is one thread-local attribute read. Ids are a random process
prefix + atomic counter, not per-call ``os.urandom`` — the getrandom
syscall costs microseconds on older kernels, and id minting sits on
the submit path of every request across every client thread.
"""

from __future__ import annotations

import itertools
import os
import threading
from contextlib import contextmanager, nullcontext
from typing import Any, ContextManager, Iterator

# one syscall at import; uniqueness within the process comes from the
# counter (itertools.count.__next__ is atomic under the GIL), across
# processes from the 8-hex random prefix
_ID_PREFIX = os.urandom(4).hex()
_id_counter = itertools.count(1)


def _reseed_ids() -> None:
    # a fork()ed child inherits both prefix and counter and would mint
    # byte-identical ids to its siblings — re-seed in the child so the
    # cross-process-uniqueness contract survives multiprocessing(fork)
    global _ID_PREFIX, _id_counter
    _ID_PREFIX = os.urandom(4).hex()
    _id_counter = itertools.count(1)


if hasattr(os, "register_at_fork"):  # POSIX only; no fork elsewhere
    os.register_at_fork(after_in_child=_reseed_ids)


def _new_id() -> str:
    return f"{_ID_PREFIX}{next(_id_counter):08x}"


class TraceContext:
    """One traced unit of work: a request, or the batch serving many.

    ``breakdown`` is filled by the batcher as the request moves
    through the pipeline (``queue_ms``, ``batch_ms``, ``forward_ms``,
    ``total_ms``, ``batch_size``, ``bucket``, ``model_version``,
    ``error``) and is complete by the time the request's future
    resolves. ``annotations`` collects facts attached via
    :func:`annotate` while the context is installed (each key holds
    the LIST of values seen — a slab-split forward annotates
    ``bucket`` once per slab). ``links`` (batch contexts only) are the
    trace ids of the member requests.
    """

    __slots__ = (
        "trace_id", "request_id", "links", "annotations",
        "breakdown", "journey", "_span_stack",
    )

    def __init__(
        self,
        *,
        trace_id: str | None = None,
        request_id: str | None = None,
        links: tuple[str, ...] = (),
    ) -> None:
        self.trace_id = trace_id or _new_id()
        self.request_id = request_id
        self.links = tuple(links)
        self.annotations: dict[str, list] = {}
        self.breakdown: dict[str, Any] = {}
        # pre-batcher journey stage timings (``admission_ms``,
        # ``wfq_ms``, ``restore_ms``, ``dispatch_ms`` + ``tenant``),
        # stamped by the tenancy fleet; None for traces minted by the
        # batcher itself — the breakdown fix-up gates on this so a
        # single-model process pays one attribute read, nothing more
        self.journey: dict[str, Any] | None = None
        # span ids open on THIS context, innermost last; only the
        # installing thread touches it (contexts are installed on one
        # thread at a time — the submit thread, then the worker)
        self._span_stack: list[str] = []

    # -- span linkage (called by telemetry.spans) ----------------------

    def begin_span(self) -> dict[str, Any]:
        """Mint a span id nested under the current one; returns the
        identity fields the span event should carry."""
        parent = self._span_stack[-1] if self._span_stack else None
        span_id = _new_id()
        self._span_stack.append(span_id)
        fields: dict[str, Any] = {
            "trace_id": self.trace_id,
            "span_id": span_id,
        }
        if parent is not None:
            fields["parent_id"] = parent
        if self.request_id is not None:
            fields["request_id"] = self.request_id
        if self.links:
            fields["links"] = list(self.links)
        return fields

    def end_span(self) -> None:
        if self._span_stack:
            self._span_stack.pop()

    def __repr__(self) -> str:  # debugger/REPL affordance
        rid = f", request_id={self.request_id!r}" if self.request_id else ""
        return f"TraceContext(trace_id={self.trace_id!r}{rid})"


def request_context() -> TraceContext:
    """A fresh per-request context (trace id + request id)."""
    return TraceContext(request_id=f"req-{_new_id()}")


def batch_context(members: list["TraceContext"]) -> TraceContext:
    """A context for one coalesced micro-batch, linked to every member
    request's trace so batch-level spans resolve from any of them."""
    return TraceContext(links=tuple(m.trace_id for m in members))


class _Current(threading.local):
    ctx: "TraceContext | None" = None


_current = _Current()


def current() -> TraceContext | None:
    """The context installed on this thread, or None."""
    return _current.ctx


# reusable + reentrant: one shared null manager serves every
# disabled-mode `with tracing.use(None)` without a per-request
# generator allocation (the cost-contract analog of telemetry.span's
# cached no-op singleton)
_NULL_CM: ContextManager[None] = nullcontext()


@contextmanager
def _install(ctx: TraceContext) -> Iterator[TraceContext]:
    prev = _current.ctx
    _current.ctx = ctx
    try:
        yield ctx
    finally:
        _current.ctx = prev


def use(ctx: TraceContext | None) -> ContextManager[TraceContext | None]:
    """Install ``ctx`` as this thread's current trace context for the
    block. ``use(None)`` is a no-op passthrough returning a shared
    null manager — zero allocation, so the disabled path keeps one
    code shape at the call sites without paying for it."""
    if ctx is None:
        return _NULL_CM
    return _install(ctx)


def annotate(**facts: Any) -> None:
    """Attach facts to the current context (no-op when none is
    installed). Each key accumulates a list — call sites that run more
    than once per context (slab-split forwards) append rather than
    overwrite."""
    ctx = _current.ctx
    if ctx is None:
        return
    for k, v in facts.items():
        ctx.annotations.setdefault(k, []).append(v)
