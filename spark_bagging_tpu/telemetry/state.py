"""Process-wide telemetry state: the enabled flags, THE registry, and
the active event sinks.

Kept in its own module so ``spans``/``sinks``/the package facade can
all import it without cycles. Host-side counters are ON by default
(cheap: one bool check + a locked float add on paths that already do
device dispatch); device-sync span timing is OPT-IN (the barrier
serializes the pipeline it measures). ``enabled = False`` turns every
telemetry call site into a single attribute read.
"""

from __future__ import annotations

import threading

from spark_bagging_tpu.telemetry.registry import Registry


class TelemetryState:
    def __init__(self) -> None:
        self.enabled = True
        self.device_sync = False
        self.registry = Registry()
        self._sinks: list = []
        self._lock = threading.Lock()

    def add_sink(self, sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    def emit(self, event: dict) -> None:
        """Deliver one event to every active sink (usually 0 or 1 —
        an open ``telemetry.capture()``). Cheap when no sink is open."""
        if not self._sinks:
            return
        with self._lock:
            sinks = list(self._sinks)
        for s in sinks:
            s.emit(event)


STATE = TelemetryState()
