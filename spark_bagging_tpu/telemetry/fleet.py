"""Fleet observability plane — N processes, one merged pane of glass.

PR 9 made serving N-process (``serve_config.json`` manifests,
version-consistent rolling swaps) and PR 11 made each process survive
faults, but every observability surface so far is single-process: one
registry, one ``/varz``, one flight recorder. This module is the
divide-and-merge half — the same aggregation structure bagging itself
rests on (*A Scalable Bootstrap for Massive Data*, arxiv 1112.5016):
each peer computes its own complete statistics, and a pull-based
:class:`FleetAggregator` merges them EXACTLY rather than averaging
summaries.

**Merge semantics** (:func:`merge_snapshots` — also the offline
``python -m spark_bagging_tpu.telemetry dump --merge`` code path):

- **counters** sum across fresh peers (same name + labels);
- **gauges** keep per-peer values under a ``process=`` label and gain
  ``fleet="min"/"max"/"sum"`` aggregate series (a fleet-wide queue
  depth is three different questions — worst peer, best peer, total —
  and collapsing them to one number answers none);
- **histograms** merge bucket-wise via :meth:`Histogram.merge` —
  exact by construction, so fleet p50/p95/p99 are computed from the
  union of the peers' bucket counts. Percentiles are NEVER averaged
  (the mean of two p99s is not a p99 of anything).

Peers are scraped over their PR-5 exposition endpoint (``/varz`` JSON,
loopback HTTP — :class:`HTTPPeer`) or in-process
(:class:`RegistryPeer`: the unit-test and ``replay --fleet`` seam).
A peer whose scrape times out or errors is marked **stale**: excluded
from quorum and from gauge merges (a stale queue depth is a stale
lie), while its CUMULATIVE series — counters, histograms — stay in
the merge frozen at their last-known values (a counter is a lower
bound that never lies, and dropping it would make the merged sum
non-monotonic: the peer's history would vanish and reappear on
recovery, which a rate rule reads as a failure spike). A stale peer
is never merged as zeros — absent data is not zero data — and its
outage is visible as ``sbt_fleet_scrape_age_seconds`` plus a counted
``sbt_fleet_scrape_failures_total``. Quorum health mirrors PR 11's
degraded semantics: majority of peers fresh+healthy ⇒ quorum holds
(``degraded`` when any peer is lost), below majority ⇒ ``/fleet/
healthz`` serves 503.

**Swap convergence** is first-class: per-peer live versions surface as
``sbt_fleet_version{model=,process=}``, ``sbt_fleet_version_skew`` is
max−min across the peers' LAST-KNOWN versions (0 = converged; the
unlabeled twin is the max over models, what
:func:`default_fleet_rules`' skew-stalled rule watches) — last-known,
not fresh-only, so a peer that wedges mid-upgrade and stops answering
scrapes holds the excursion open instead of faking convergence — and
each skew excursion's duration lands in the
``sbt_fleet_convergence_seconds`` histogram — time-to-convergence of
a rolling swap, measured not inferred.

**Incidents**: :func:`correlate_incidents` flattens the peers' flight
feeds (dump records + ring trigger events, scraped with ``/varz``)
plus the aggregator's own alert firings into one time-ordered
timeline and groups same-trigger events inside a correlation window
into single incidents — the "did peer 1's flight dump line up with
peer 3's shed burst?" view, served at ``/fleet/incidents``.

Everything is clock-injectable (``tick(now=...)``) and thread-free:
scrapes run when a ``/fleet/*`` route (or the replay drill) ticks the
aggregator, which is what lets ``benchmarks/replay.py --fleet N``
assert byte-identical merged digests, skew transcripts, and incident
timelines across repeats.
"""

from __future__ import annotations

import hashlib
import json
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Iterable

from spark_bagging_tpu.analysis.locks import make_lock
from spark_bagging_tpu.telemetry.registry import (
    Histogram,
    _label_key,
    histogram_entry,
    histogram_from_entry,
    snapshot_quantiles,
)
from spark_bagging_tpu.telemetry.state import STATE

#: the deterministic plane of a merged snapshot: series whose values
#: are a pure function of (workload, seed, plan) under the virtual
#: clock — what the ``--fleet`` replay digest covers. Wall-clock
#: series (latencies, compile seconds, process RSS) and cache-state-
#: dependent counters (compiles: the program cache makes repeat 1
#: compile and repeat 2 adopt) are deliberately excluded.
FLEET_DIGEST_SERIES: tuple[str, ...] = (
    "sbt_serving_requests_total",
    "sbt_serving_rows_total",
    "sbt_serving_batches_total",
    "sbt_serving_padding_rows_total",
    "sbt_serving_batch_fill_ratio",
    "sbt_serving_shed_total",
    "sbt_serving_overloaded_total",
    "sbt_serving_request_failures_total",
    "sbt_serving_retries_total",
    "sbt_serving_batch_bisects_total",
    "sbt_serving_model_version",
    "sbt_serving_swaps_total",
    "sbt_fleet_peers",
    "sbt_fleet_peers_fresh",
    "sbt_fleet_peers_stale",
    "sbt_fleet_quorum",
    "sbt_fleet_scrapes_total",
    "sbt_fleet_scrape_failures_total",
    "sbt_fleet_scrape_age_seconds",
    "sbt_fleet_version",
    "sbt_fleet_version_skew",
    "sbt_fleet_convergence_seconds",
    # capacity plane [ISSUE 16]: demand counters are workload-pure
    # (fed per packed batch under the virtual clock); the byte gauges
    # are toolchain-dependent measurements and stay out of the digest
    "sbt_capacity_demand_requests_total",
    "sbt_capacity_demand_rows_total",
)


@contextmanager
def use_registry(registry):
    """Temporarily install ``registry`` as THE process metrics registry
    — the seam that lets one process simulate N: ``replay --fleet``
    drives each virtual peer's batcher/model-registry inside its own
    ``use_registry(reg_i)`` scope, so every ``sbt_*`` series lands in
    that peer's registry exactly as it would in a real peer process.
    Single-threaded virtual-clock drills only: the swap is a plain
    module-global write, visible to every thread."""
    prev = STATE.registry
    STATE.registry = registry
    try:
        yield registry
    finally:
        STATE.registry = prev


def _emit(event: dict) -> None:
    if STATE.enabled and STATE._sinks:
        event.setdefault("ts", time.time())
        STATE.emit(event)


# -- peers ---------------------------------------------------------------

class HTTPPeer:
    """A peer process scraped over its exposition endpoint: one
    ``GET <base_url>/varz`` per scrape (metrics + health + flight feed
    in a single round-trip). Timeouts and HTTP errors raise — the
    aggregator turns them into staleness, never into zeros.
    ``remote = True`` tells the aggregator this scrape does network
    I/O, so a pass scrapes it concurrently with the other remote
    peers — N dead peers cost ONE timeout, not N stacked ones."""

    remote = True

    def __init__(self, name: str, base_url: str, *,
                 timeout_s: float = 2.0) -> None:
        self.name = str(name)
        self.base_url = str(base_url).rstrip("/")
        self.timeout_s = float(timeout_s)

    def scrape(self) -> dict:
        import urllib.request

        with urllib.request.urlopen(
            self.base_url + "/varz", timeout=self.timeout_s
        ) as resp:
            if resp.status != 200:
                raise RuntimeError(
                    f"peer {self.name!r} /varz returned {resp.status}"
                )
            return json.loads(resp.read().decode("utf-8"))

    def __repr__(self) -> str:
        return f"HTTPPeer({self.name!r}, {self.base_url!r})"


class RegistryPeer:
    """An in-process peer: a bare :class:`telemetry.registry.Registry`
    (plus optional health callable and flight recorder) dressed up as
    a scrape target. The unit-test and ``replay --fleet`` seam — the
    virtual-fleet drill gives each simulated peer one of these."""

    def __init__(self, name: str, registry, *,
                 health: Callable[[], dict] | None = None,
                 recorder=None) -> None:
        self.name = str(name)
        self._registry = registry
        self._health = health
        self._recorder = recorder

    def scrape(self) -> dict:
        out: dict[str, Any] = {"metrics": self._registry.snapshot()}
        if self._health is not None:
            out["health"] = dict(self._health())
        if self._recorder is not None:
            out["flight"] = self._recorder.timeline_feed()
        return out

    def __repr__(self) -> str:
        return f"RegistryPeer({self.name!r})"


# -- the exact merge -----------------------------------------------------

def _value_entry(name: str, kind: str, labels: dict, v: float) -> dict:
    return {"name": name, "kind": kind, "labels": dict(labels),
            "value": v}


def _entry_sort_key(e: dict):
    return (e["name"], tuple(sorted(e["labels"].items())))


def merge_snapshots(
    named_snapshots: Iterable[tuple[str, list[dict]]],
) -> tuple[list[dict], list[str]]:
    """Merge per-process registry snapshots into one fleet snapshot.

    ``named_snapshots`` is ``[(process_name, snapshot_entries), ...]``
    where each snapshot is the :meth:`Registry.snapshot` JSON shape.
    Returns ``(merged_entries, dropped_names)``: counters summed,
    gauges per-peer ``process=``-labeled plus ``fleet=min/max/sum``
    aggregates, histograms merged bucket-wise (exact). A series whose
    peers disagree on metric kind or histogram bounds cannot be merged
    exactly and is dropped whole — its names come back in
    ``dropped_names`` so callers can count the conflict instead of
    publishing a lie."""
    counters: dict[tuple, float] = {}
    gauges: dict[tuple, list[tuple[str, float]]] = {}
    hists: dict[tuple, Histogram] = {}
    kinds: dict[tuple, str] = {}
    dropped_keys: set[tuple] = set()
    for pname, snap in named_snapshots:
        for e in snap:
            name = e["name"]
            labels = e.get("labels") or {}
            key = (name, _label_key(labels), )
            if key in dropped_keys:
                continue
            kind = e["kind"]
            prev = kinds.setdefault(key, kind)
            if prev != kind:
                dropped_keys.add(key)
                continue
            if kind == "counter":
                counters[key] = counters.get(key, 0.0) + float(e["value"])
            elif kind == "gauge":
                if "process" in labels or "fleet" in labels:
                    # the merge OWNS these two label names on gauges;
                    # a pre-labeled series (e.g. re-merging an already
                    # merged snapshot) would silently collide into
                    # duplicate-label entries — a conflict, like
                    # kind/bounds disagreements, never a quiet lie
                    dropped_keys.add(key)
                    continue
                gauges.setdefault(key, []).append(
                    (str(pname), float(e["value"]))
                )
            else:
                h = histogram_from_entry(e)
                mine = hists.get(key)
                if mine is None:
                    hists[key] = h
                else:
                    try:
                        mine.merge(h)
                    except ValueError:
                        dropped_keys.add(key)
    for key in dropped_keys:
        counters.pop(key, None)
        gauges.pop(key, None)
        hists.pop(key, None)
    out: list[dict] = []
    for (name, lk), v in counters.items():
        out.append(_value_entry(name, "counter", dict(lk), v))
    for (name, lk), per_peer in gauges.items():
        labels = dict(lk)
        values = [v for _, v in per_peer]
        for pname, v in per_peer:
            out.append(_value_entry(
                name, "gauge", {**labels, "process": pname}, v
            ))
        for agg, v in (("min", min(values)), ("max", max(values)),
                       ("sum", sum(values))):
            out.append(_value_entry(
                name, "gauge", {**labels, "fleet": agg}, v
            ))
    for (name, lk), h in hists.items():
        out.append(histogram_entry(name, dict(lk), h))
    out.sort(key=_entry_sort_key)
    return out, sorted({name for name, _ in dropped_keys})


def merged_digest(entries: list[dict],
                  series: Iterable[str] | None = FLEET_DIGEST_SERIES,
                  ) -> str:
    """Canonical sha256 of a merged snapshot's deterministic plane.
    ``series`` is an inclusion list (None = everything); exemplars are
    stripped — they carry wall-clock timestamps and process-global
    trace ids, which are real data but not replay-stable identity."""
    include = set(series) if series is not None else None
    keep = []
    for e in entries:
        if include is not None and e["name"] not in include:
            continue
        keep.append({k: v for k, v in e.items()
                     if k not in ("exemplars", "slow_exemplars")})
    keep.sort(key=_entry_sort_key)
    return hashlib.sha256(
        json.dumps(keep, sort_keys=True).encode()
    ).hexdigest()


# -- incident correlation ------------------------------------------------

def correlate_incidents(
    feeds: Iterable[tuple[str, dict | None]],
    *,
    window_s: float = 5.0,
    clock_key: str = "ts",
) -> tuple[list[dict], list[dict]]:
    """Order the peers' incident feeds into one timeline and group
    same-trigger events into incidents.

    Each feed is the ``flight`` section a peer's ``/varz`` exposes
    (:meth:`FlightRecorder.timeline_feed`): ``dumps`` records and ring
    ``events``. Events are stamped from ``clock_key`` — ``"ts"``
    (wall clock; production, where all peers share one host clock) or
    ``"now"`` (the alert engine's injectable clock; what the replay
    drill uses for byte-stable timelines). Entries without that stamp
    are excluded rather than mixed across clocks.

    Grouping: events sharing a trigger identity — ``(kind, key)``
    where key is the alert rule / model / kind — chain into one
    incident while each is within ``window_s`` of the incident's last
    event. Returns ``(incidents, flat_events)``, both time-ordered;
    the flat timeline is what lets an operator line a flight dump on
    one peer up against sheds on another even when they are distinct
    incidents."""
    flat: list[dict] = []
    for peer, feed in feeds:
        if not feed:
            continue
        for d in feed.get("dumps", ()):
            t = d.get(clock_key)
            if t is None:
                continue
            kind = d.get("kind") or "flight_dump"
            flat.append({
                "t": float(t), "peer": str(peer), "kind": kind,
                "key": d.get("rule") or d.get("model") or kind,
                "type": "flight_dump", "path": d.get("path"),
            })
        for ev in feed.get("events", ()):
            t = ev.get(clock_key)
            if t is None:
                continue
            kind = ev.get("kind") or "event"
            entry = {
                "t": float(t), "peer": str(peer), "kind": kind,
                "key": ev.get("rule") or ev.get("model") or kind,
                "type": "event",
            }
            for k in ("rule", "model", "severity", "value", "version",
                      "trace_id"):
                if k in ev:
                    entry[k] = ev[k]
            flat.append(entry)
    flat.sort(key=lambda e: (e["t"], e["peer"], e["kind"],
                             str(e["key"])))
    incidents: list[dict] = []
    open_by_key: dict[tuple, dict] = {}
    for e in flat:
        gk = (e["kind"], str(e["key"]))
        inc = open_by_key.get(gk)
        if inc is None or e["t"] - inc["t_end"] > window_s:
            inc = {
                "kind": e["kind"], "key": e["key"],
                "t_start": e["t"], "t_end": e["t"],
                "peers": [], "count": 0, "events": [],
            }
            incidents.append(inc)
            open_by_key[gk] = inc
        inc["t_end"] = e["t"]
        inc["count"] += 1
        if e["peer"] not in inc["peers"]:
            inc["peers"].append(e["peer"])
        inc["events"].append(e)
    incidents.sort(key=lambda i: (i["t_start"], i["kind"],
                                  str(i["key"])))
    return incidents, flat


def timeline_digest(incidents: list[dict]) -> str:
    """sha256 over the deterministic projection of a timeline — the
    identity the ``--fleet`` drill asserts across repeats."""
    proj = [
        [i["kind"], str(i["key"]), sorted(i["peers"]), i["count"],
         round(i["t_start"], 9), round(i["t_end"], 9)]
        for i in incidents
    ]
    return hashlib.sha256(
        json.dumps(proj, sort_keys=True).encode()
    ).hexdigest()


# -- the aggregator ------------------------------------------------------

class _Sample:
    """What :meth:`FleetAggregator.peek` hands the alert engine: the
    merged series' kind + value (counters/gauges only — rules never
    sample histograms)."""

    __slots__ = ("kind", "value")

    def __init__(self, kind: str, value: float) -> None:
        self.kind = kind
        self.value = value


class _PeerStatus:
    __slots__ = ("name", "ok", "error", "last_attempt_t", "last_ok_t",
                 "failures", "snapshot")

    def __init__(self, name: str) -> None:
        self.name = name
        self.ok: bool | None = None      # None = never scraped
        self.error: str | None = None
        self.last_attempt_t: float | None = None
        self.last_ok_t: float | None = None
        self.failures = 0
        self.snapshot: dict | None = None  # last SUCCESSFUL /varz


# sbt-lint: shared-state
class FleetAggregator:
    """Pull-based scrape-and-merge over N peers (see module doc).

    Clock-injectable and thread-free: call :meth:`tick` from a scrape
    handler, a loop, or a replay's virtual clock. ``interval_s`` rate-
    limits real scrapes (a tight ``curl`` loop on ``/fleet/metrics``
    must not hammer every peer); ``tick(force=True)`` bypasses it.
    ``rules`` (e.g. :func:`default_fleet_rules`) install an
    :class:`~spark_bagging_tpu.telemetry.alerts.AlertEngine` sampling
    the MERGED series via :meth:`peek`, evaluated once per scrape
    pass on the same injected clock.
    """

    def __init__(
        self,
        peers: Iterable[HTTPPeer | RegistryPeer],
        *,
        interval_s: float = 5.0,
        stale_after_s: float | None = None,
        quorum: int | None = None,
        correlation_window_s: float = 5.0,
        rules: Iterable | None = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.peers = tuple(peers)
        if not self.peers:
            raise ValueError("a fleet aggregator needs at least one peer")
        names = [p.name for p in self.peers]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate peer names: {sorted(names)}")
        if quorum is not None and not 1 <= quorum <= len(self.peers):
            raise ValueError(
                f"quorum must be in [1, {len(self.peers)}], got {quorum}"
            )
        self.interval_s = float(interval_s)
        # staleness by AGE, for when ticks keep running but one peer's
        # last success recedes into the past; a FAILED last attempt
        # marks the peer stale immediately (the PR-11 stance: degrade
        # on the fault, heal on the next success)
        self.stale_after_s = (float(stale_after_s)
                              if stale_after_s is not None
                              else max(3.0 * self.interval_s, 10.0))
        self.quorum = (int(quorum) if quorum is not None
                       else len(self.peers) // 2 + 1)
        self.correlation_window_s = float(correlation_window_s)
        self._clock = clock
        # _scrape_lock serializes whole scrape passes (network I/O
        # outside _lock); _lock guards the merged state. Order is
        # always _scrape_lock -> _lock.
        self._scrape_lock = make_lock("telemetry.fleet.scrape")
        self._lock = make_lock("telemetry.fleet")
        self._status: dict[str, _PeerStatus] = {
            p.name: _PeerStatus(p.name) for p in self.peers
        }
        self._last_tick: float | None = None
        self._merged: list[dict] = []
        self._dropped: list[str] = []
        self._index: dict[tuple, _Sample] = {}
        self._scrapes = 0
        self._conflicts = 0
        self._skew: dict[str, float] = {}
        self._versions: dict[str, dict[str, float]] = {}
        self._skew_since: dict[str, float] = {}
        self._convergence: dict[str, list[float]] = {}
        self._conv_hists: dict[str, Histogram] = {}
        self._alert_log: deque[dict] = deque(maxlen=256)
        rules = tuple(rules) if rules is not None else ()
        if rules:
            from spark_bagging_tpu.telemetry.alerts import AlertEngine

            self.alerts = AlertEngine(rules, registry=self)
        else:
            self.alerts = None

    # -- sampling view (the alert engine's registry) -------------------

    def peek(self, name: str, labels: dict | None = None):
        """The merged series' current sample, or None — the same
        absent-is-not-zero contract :meth:`Registry.peek` gives the
        alert engine, over the LATEST merged snapshot."""
        with self._lock:
            return self._index.get((name, _label_key(labels)))

    # -- the tick ------------------------------------------------------

    def tick(self, now: float | None = None, *,
             force: bool = False) -> bool:
        """Scrape-and-merge if ``interval_s`` has elapsed (or
        ``force``). Returns whether a pass ran. ``now`` injects the
        clock (virtual replay); default is the monotonic clock."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            due = (force or self._last_tick is None
                   or now - self._last_tick >= self.interval_s)
            if due:
                self._last_tick = now
        if due:
            self.scrape_all(now)
        return due

    def scrape_all(self, now: float | None = None) -> None:
        """One full pass: scrape every peer, merge the fresh ones,
        recompute fleet series + version skew, evaluate the alert
        rules — all on the injected clock."""
        now = self._clock() if now is None else float(now)
        with self._scrape_lock:
            results: dict[str, tuple[bool, Any]] = {}

            def _scrape_one(p) -> None:
                try:
                    results[p.name] = (True, p.scrape())
                # sbt-lint: disable=swallowed-fault — counted (sbt_fleet_scrape_failures_total), aged, emitted, and surfaced stale in /fleet/healthz
                except Exception as e:  # noqa: BLE001 — a peer outage
                    # is DATA here, not a fault of the aggregator
                    results[p.name] = (False, e)
                    _emit({
                        "kind": "fleet_scrape_failed",
                        "peer": p.name, "error": repr(e),
                    })

            # fault probes fire FIRST, sequentially, in peer order:
            # the chaos plan's hit indices must be a pure function of
            # (tick, peer position), never of network completion order
            pending = []
            for p in self.peers:
                try:
                    import spark_bagging_tpu.faults as faults_mod

                    if faults_mod.ACTIVE is not None:
                        faults_mod.fire("fleet.scrape", peer=p.name)
                # sbt-lint: disable=swallowed-fault — counted (sbt_fleet_scrape_failures_total), aged, emitted, and surfaced stale in /fleet/healthz
                except Exception as e:  # noqa: BLE001 — an injected
                    # scrape fault IS the scripted peer outage
                    results[p.name] = (False, e)
                    _emit({
                        "kind": "fleet_scrape_failed",
                        "peer": p.name, "error": repr(e),
                    })
                    continue
                pending.append(p)
            # network peers scrape CONCURRENTLY (each urlopen can burn
            # its whole timeout — run sequentially, a half-down fleet
            # would stall a /fleet/healthz pass by timeout x dead
            # peers, tripping the external prober exactly during the
            # partial outage it exists to report); in-process peers
            # are lock-protected snapshot copies and stay inline
            remote = [p for p in pending
                      if getattr(p, "remote", False)]
            if len(remote) > 1:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(
                    max_workers=min(8, len(remote)),
                    thread_name_prefix="sbt-fleet-scrape",
                ) as pool:
                    futures = [pool.submit(_scrape_one, p)
                               for p in remote]
                    for p in pending:
                        if p not in remote:
                            _scrape_one(p)
                    for f in futures:
                        f.result()
            else:
                for p in pending:
                    _scrape_one(p)
            with self._lock:
                self._scrapes += len(self.peers)
                for name, (ok, payload) in results.items():
                    st = self._status[name]
                    st.last_attempt_t = now
                    st.ok = ok
                    if ok:
                        st.last_ok_t = now
                        st.error = None
                        st.snapshot = payload
                    else:
                        st.failures += 1
                        st.error = repr(payload)
                fresh = self._fresh_locked(now)
                fresh_names = {st.name for st in fresh}
                named: list[tuple[str, list[dict]]] = []
                for st in self._status.values():
                    snap = (st.snapshot or {}).get("metrics") or []
                    if not snap:
                        continue  # never scraped: nothing to merge
                    if st.name not in fresh_names:
                        # a stale peer's CUMULATIVE series (counters,
                        # histograms) stay in the merge at their
                        # last-known values — a counter is a lower
                        # bound that never lies, and dropping it would
                        # make the merged sum NON-MONOTONIC (the
                        # peer's whole history would vanish and then
                        # reappear on recovery, which a burn-rate rule
                        # reads as a massive failure spike). Its
                        # GAUGES drop out: a stale queue depth is a
                        # stale lie, and staleness itself is what the
                        # age gauge/quorum surface
                        snap = [e for e in snap if e["kind"] != "gauge"]
                    named.append((st.name, snap))
                merged, dropped = merge_snapshots(named)
                self._conflicts += len(dropped)
                self._dropped = dropped
                self._update_skew_locked(now)
                merged.extend(self._fleet_entries_locked(
                    fresh, now, merged_n=len(merged)
                ))
                merged.sort(key=_entry_sort_key)
                self._merged = merged
                self._index = {
                    (e["name"], _label_key(e["labels"])):
                        _Sample(e["kind"], e.get("value"))
                    for e in merged if e["kind"] != "histogram"
                }
            if self.alerts is not None:
                events = self.alerts.evaluate(now=now)
                if events:
                    with self._lock:
                        self._alert_log.extend(events)

    # -- locked helpers ------------------------------------------------

    def _fresh_locked(self, now: float) -> list[_PeerStatus]:
        return [
            st for st in self._status.values()
            if st.ok and st.last_ok_t is not None
            and now - st.last_ok_t <= self.stale_after_s
        ]

    def _update_skew_locked(self, now: float) -> None:
        # versions come from every peer's LAST-KNOWN snapshot, not
        # just the fresh set: a peer that wedges mid-upgrade at the
        # old version and stops answering scrapes must HOLD the skew
        # excursion open (that outage IS the stalled roll the
        # skew-stalled rule exists to page on) — computing over fresh
        # peers only would read skew 0, record a spurious short
        # convergence, and resolve the alert while the fleet is split
        versions: dict[str, dict[str, float]] = {}
        for st in self._status.values():
            for e in (st.snapshot or {}).get("metrics") or []:
                if e["name"] != "sbt_serving_model_version":
                    continue
                model = (e.get("labels") or {}).get("model", "")
                versions.setdefault(model, {})[st.name] = float(
                    e["value"]
                )
        skew: dict[str, float] = {}
        for model, per_peer in versions.items():
            vals = list(per_peer.values())
            skew[model] = max(vals) - min(vals)
        # convergence excursions: skew leaving 0 starts the clock for
        # that model, returning to 0 observes the duration (a model
        # that disappears mid-excursion — all reporting peers lost —
        # keeps its start; the excursion is still open)
        for model, s in skew.items():
            if s > 0 and model not in self._skew_since:
                # sbt-lint: disable=shared-state-unlocked — every caller holds self._lock (the _locked naming convention)
                self._skew_since[model] = now
            elif s == 0 and model in self._skew_since:
                dt = now - self._skew_since.pop(model)
                self._convergence.setdefault(model, []).append(dt)
                self._conv_hists.setdefault(
                    model, Histogram()
                ).observe(dt)
        # sbt-lint: disable=shared-state-unlocked — every caller holds self._lock (the _locked naming convention)
        self._skew = skew
        # sbt-lint: disable=shared-state-unlocked — every caller holds self._lock (the _locked naming convention)
        self._versions = versions

    def _fleet_entries_locked(self, fresh: list[_PeerStatus],
                              now: float, *,
                              merged_n: int) -> list[dict]:
        n = len(self.peers)
        n_fresh = len(fresh)
        healthy = sum(
            1 for st in fresh
            if bool(((st.snapshot or {}).get("health") or
                     {"healthy": True}).get("healthy", True))
        )
        out = [
            _value_entry("sbt_fleet_peers", "gauge", {}, float(n)),
            _value_entry("sbt_fleet_peers_fresh", "gauge", {},
                         float(n_fresh)),
            _value_entry("sbt_fleet_peers_stale", "gauge", {},
                         float(n - n_fresh)),
            _value_entry("sbt_fleet_quorum", "gauge", {},
                         1.0 if healthy >= self.quorum else 0.0),
            _value_entry("sbt_fleet_scrapes_total", "counter", {},
                         float(self._scrapes)),
            _value_entry("sbt_fleet_merged_series", "gauge", {},
                         float(merged_n)),
            _value_entry("sbt_fleet_merge_conflicts_total", "counter",
                         {}, float(self._conflicts)),
        ]
        for st in self._status.values():
            out.append(_value_entry(
                "sbt_fleet_scrape_failures_total", "counter",
                {"process": st.name}, float(st.failures),
            ))
            if st.last_ok_t is not None:
                # never-scraped peers get NO age series (absent, not
                # zero — and not +Inf, which JSON cannot carry and a
                # strict /fleet/varz consumer would choke on); their
                # outage is visible as fresh=False + the failure count
                out.append(_value_entry(
                    "sbt_fleet_scrape_age_seconds", "gauge",
                    {"process": st.name}, now - st.last_ok_t,
                ))
        # per-peer versions are last-known (stale peers included, like
        # the skew they feed): a version only moves forward, and the
        # wedged peer's OLD version is exactly the datum an operator
        # diagnosing a stalled roll needs to see
        for model, per_peer in self._versions.items():
            for pname, v in sorted(per_peer.items()):
                out.append(_value_entry(
                    "sbt_fleet_version", "gauge",
                    {"model": model, "process": pname}, v,
                ))
        for model, s in self._skew.items():
            out.append(_value_entry(
                "sbt_fleet_version_skew", "gauge", {"model": model}, s,
            ))
        # the unlabeled twin: max skew over models — what the generic
        # skew-stalled rule watches without knowing model names
        out.append(_value_entry(
            "sbt_fleet_version_skew", "gauge", {},
            max(self._skew.values()) if self._skew else 0.0,
        ))
        for model, h in self._conv_hists.items():
            out.append(histogram_entry(
                "sbt_fleet_convergence_seconds", {"model": model}, h,
            ))
        return out

    # -- views ---------------------------------------------------------

    def merged_snapshot(self) -> list[dict]:
        """The latest merged fleet snapshot (entry dicts, sorted) —
        what ``/fleet/metrics`` renders."""
        with self._lock:
            return [dict(e) for e in self._merged]

    def version_skew(self) -> dict[str, float]:
        with self._lock:
            return dict(self._skew)

    def convergence_observations(self) -> dict[str, list[float]]:
        """Per-model skew-excursion durations observed so far (the raw
        observations behind ``sbt_fleet_convergence_seconds``)."""
        with self._lock:
            return {m: list(v) for m, v in self._convergence.items()}

    def fleet_health(self, now: float | None = None) -> dict[str, Any]:
        """Quorum health over peer healthz + scrape staleness:
        ``healthy`` while at least ``quorum`` peers are fresh AND
        report healthy (``degraded`` whenever any peer is lost or
        unhealthy) — PR 11's serve-what-survives semantics at fleet
        scope."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            fresh = {st.name for st in self._fresh_locked(now)}
            peers: dict[str, dict] = {}
            healthy_n = 0
            for st in self._status.values():
                is_fresh = st.name in fresh
                peer_health = ((st.snapshot or {}).get("health")
                               or {"healthy": True})
                ok = is_fresh and bool(peer_health.get("healthy", True))
                healthy_n += 1 if ok else 0
                peers[st.name] = {
                    "fresh": is_fresh,
                    "healthy": ok,
                    "failures": st.failures,
                    "age_s": (now - st.last_ok_t
                              if st.last_ok_t is not None else None),
                    "error": st.error,
                }
            quorum_met = healthy_n >= self.quorum
            return {
                "healthy": quorum_met,
                "degraded": healthy_n < len(self.peers),
                "fresh": len(fresh),
                "healthy_peers": healthy_n,
                "required": self.quorum,
                "configured": len(self.peers),
                "peers": peers,
            }

    def fleet_varz(self, now: float | None = None) -> dict[str, Any]:
        """The ``/fleet/varz`` JSON: peer status, quorum health, skew,
        and the merged snapshot with per-histogram quantiles computed
        from the MERGED bucket counts (exact — never an average of
        peer percentiles)."""
        now_c = self._clock() if now is None else float(now)
        with self._lock:
            merged = [dict(e) for e in self._merged]
            dropped = list(self._dropped)
            skew = dict(self._skew)
            convergence = {m: list(v)
                           for m, v in self._convergence.items()}
        for e in merged:
            if e["kind"] == "histogram":
                e["quantiles"] = snapshot_quantiles(e)
        out: dict[str, Any] = {
            "ts": time.time(),
            "interval_s": self.interval_s,
            "stale_after_s": self.stale_after_s,
            "health": self.fleet_health(now_c),
            "version_skew": skew,
            "convergence_seconds": convergence,
            "merge_dropped": dropped,
            "metrics": merged,
        }
        if self.alerts is not None:
            out["alerts"] = self.alerts.state()
        return out

    def incident_timeline(self, *, window_s: float | None = None,
                          clock_key: str = "ts") -> dict[str, Any]:
        """The ``/fleet/incidents`` JSON: every peer's flight feed
        (from its last successful scrape — a stale peer's last-known
        dumps still matter, they are often the incident) plus the
        aggregator's own alert firings, correlated into incidents."""
        with self._lock:
            feeds: list[tuple[str, dict | None]] = [
                (st.name, (st.snapshot or {}).get("flight"))
                for st in self._status.values()
            ]
            feeds.append(("fleet", {"dumps": [],
                                    "events": list(self._alert_log)}))
        w = (self.correlation_window_s if window_s is None
             else float(window_s))
        incidents, events = correlate_incidents(
            feeds, window_s=w, clock_key=clock_key,
        )
        return {
            "window_s": w,
            "clock": clock_key,
            "n_incidents": len(incidents),
            "incidents": incidents,
            "events": events,
            "digest": timeline_digest(incidents),
        }


# -- the default alert pack ----------------------------------------------

def default_fleet_rules(
    *,
    skew_fast_s: float = 60.0,
    skew_slow_s: float = 600.0,
    peer_fast_s: float = 30.0,
    peer_slow_s: float = 120.0,
    burn_threshold_per_s: float = 0.02,
    burn_fast_s: float = 60.0,
    burn_slow_s: float = 600.0,
    cooldown_s: float = 300.0,
    name_prefix: str = "fleet-",
) -> list:
    """The fleet plane's starter rules, evaluated over MERGED series:

    - ``skew-stalled``: version skew stayed above 0 across both
      windows — a rolling swap started and never converged (a healthy
      roll's excursion is shorter than ``skew_fast_s``);
    - ``peer-lost``: at least one peer stale across both windows (a
      single scrape blip inside the fast window never pages);
    - ``burn-rate``: the fleet-wide request-failure counter's
      per-second rate breached in both windows (multi-window burn
      rate over the SUMMED counter — one peer failing everything and
      five peers each failing a sixth look identical here, which is
      the point).
    """
    from spark_bagging_tpu.telemetry.alerts import AlertRule

    return [
        AlertRule(
            f"{name_prefix}skew-stalled", "sbt_fleet_version_skew",
            threshold=0.0, kind="value", op=">",
            fast_window_s=skew_fast_s, slow_window_s=skew_slow_s,
            cooldown_s=cooldown_s,
            description="model version skew across the fleet never "
                        "returned to 0 — a rolling swap is stalled",
        ),
        AlertRule(
            f"{name_prefix}peer-lost", "sbt_fleet_peers_stale",
            threshold=0.0, kind="value", op=">",
            fast_window_s=peer_fast_s, slow_window_s=peer_slow_s,
            cooldown_s=cooldown_s,
            description="one or more peers stopped answering scrapes "
                        "(stale: excluded from merge and quorum)",
        ),
        AlertRule(
            f"{name_prefix}burn-rate",
            "sbt_serving_request_failures_total",
            threshold=burn_threshold_per_s, kind="rate", op=">",
            fast_window_s=burn_fast_s, slow_window_s=burn_slow_s,
            cooldown_s=cooldown_s,
            description="fleet-wide request failure rate is burning "
                        "error budget in both windows",
        ),
    ]


# -- process default -----------------------------------------------------

_default: FleetAggregator | None = None
_default_lock = make_lock("telemetry.fleet.default")


def install(aggregator: FleetAggregator) -> FleetAggregator:
    """Install the process-default aggregator — what the ``/fleet/*``
    scrape routes serve and tick. Replaces any prior default."""
    global _default
    with _default_lock:
        _default = aggregator
    return aggregator


def get() -> FleetAggregator | None:
    return _default


def uninstall() -> None:
    global _default
    with _default_lock:
        _default = None
