"""Unified telemetry: run registry, phase spans, JSONL log, Prometheus.

The reference inherits observability from Spark — ``Instrumentation``
logging, Spark-UI stage views, metrics sinks [SURVEY §5]. This package
is the TPU-native equivalent, one subsystem with three layers:

1. **Registry** (``registry.py``) — process-wide, thread-safe counters,
   gauges, and log-scale histograms (``sbt_*`` metric names): compile
   seconds, h2d bytes, chunk latencies, replicas fitted, compile-cache
   hits/misses, prefetch stalls, checkpoint bytes, OOB evaluations,
   and the online-serving series (``sbt_serving_*``: requests, rows,
   batches, queue depth, batch fill, padding waste, compile count,
   request latency, overload rejections, swaps — serving/).
2. **Spans** (``spans.py``) — nestable phase spans
   (``with telemetry.span("compile"): ...``) recording wall-clock per
   phase; ``phase()`` composes with ``jax.named_scope`` so host spans
   and device traces share names. Device-sync timing is opt-in.
3. **Sinks** (``sinks.py``) — ``capture()`` opens a run whose events
   (spans + metric flushes) land in memory and, optionally, a
   schema-versioned JSONL file; ``render_prometheus()`` dumps the
   registry in Prometheus text format (also:
   ``python -m spark_bagging_tpu.telemetry dump``). Run artifacts
   default into ``telemetry_dir()`` (``$SBT_TELEMETRY_DIR``, else
   ``./telemetry/``).
4. **Live plane** (``server.py`` / ``tracing.py`` / ``recorder.py``) —
   an opt-in stdlib HTTP exposition server (``/metrics``, ``/healthz``,
   ``/varz``, ``/debug/spans``, ``/debug/runs``; start with
   ``SBT_METRICS_PORT`` or :func:`start_server`), per-request trace
   contexts threading the serving path (every served future exposes
   ``future.trace`` with a queue/batch/forward timing breakdown), and
   a ring-buffer flight recorder that dumps ``flight_<ts>.json`` on
   serving faults.
5. **Model-quality plane** (``quality.py`` / ``alerts.py``) —
   streaming drift detection against a fit-time reference profile
   (``sbt_quality_*`` PSI/KS gauges, ensemble-disagreement sampling)
   plus a declarative burn-rate alert engine over live registry
   series (``sbt_alerts_*``; ``alert_fired`` events trigger the
   flight recorder). Served at ``/debug/drift`` and ``/alerts``.
6. **Performance attribution plane** (``perf.py``) — opt-in per-stage
   cost accounting off the request breakdowns (``sbt_perf_stage_*``),
   a measured per-bucket cost model (seconds-per-row, achieved
   FLOP/s, serving MFU), the tail-latency explainer
   (``/debug/tail``: deterministic verdicts joining slow requests
   with concurrent process events), and on-demand live device
   profiling (``/debug/profile``, single-flight + auto-stop).

Cost contract: **zero overhead when disabled** — every instrumentation
site in the engines guards on :func:`enabled` (one attribute read) or
goes through :func:`span`, which returns a shared no-op context
manager when disabled. Host-side counters are ON by default (they sit
on paths that already cross the host/device boundary); the event
stream only materializes inside an open :func:`capture`.

Typical use::

    from spark_bagging_tpu import telemetry

    with telemetry.capture("telemetry.jsonl") as run:
        clf.fit(X, y)
    run.spans("compile")                 # recorded phase spans
    print(telemetry.render_prometheus())  # scrape-able metrics dump
"""

from __future__ import annotations

from spark_bagging_tpu.telemetry.registry import (
    QUANTILES,
    Registry,
    SERIES_HELP,
    render_prometheus as _render_snapshot,
)
from spark_bagging_tpu.telemetry.sinks import (
    SCHEMA_VERSION,
    Run,
    capture,
    capture_open as _capture_open,
    current_run,
    default_log_path,
    last_metrics_snapshot,
    read_events,
    runs,
    telemetry_dir,
)
from spark_bagging_tpu.telemetry.spans import phase, span
from spark_bagging_tpu.telemetry.state import STATE as _state
from spark_bagging_tpu.telemetry import (
    alerts,
    fleet,
    history,
    perf,
    quality,
    recorder,
    slo,
    tracing,
    workload,
)

# the exposition server's names resolve lazily (module __getattr__
# below): its http.server import chain costs ~100ms of stdlib, which
# `import spark_bagging_tpu` consumers that never serve must not pay
_SERVER_ATTRS = ("start_server", "stop_server", "server_address")

__all__ = [
    "SCHEMA_VERSION", "SERIES_HELP", "QUANTILES", "Run", "capture",
    "current_run", "enabled", "enable", "disable", "set_device_sync",
    "device_sync_enabled", "span", "phase", "inc", "inc_many",
    "set_gauge",
    "observe", "emit_event", "registry", "render_prometheus",
    "read_events", "last_metrics_snapshot", "runs",
    "record_fit_report", "Registry", "reset", "telemetry_dir",
    "default_log_path", "tracing", "recorder", "workload", "slo",
    "quality", "alerts", "fleet", "perf", "history",
    "sinks_active", "arrival_events_wanted", "start_server",
    "stop_server", "server_address",
]


def enabled() -> bool:
    """THE hot-path gate: every engine instrumentation site checks this
    (or calls :func:`span`, which does) before doing any work."""
    return _state.enabled


def enable() -> None:
    _state.enabled = True


def disable() -> None:
    """Turn all telemetry recording off (named_scope device annotations
    from :func:`phase` remain — they predate this subsystem)."""
    _state.enabled = False


def set_device_sync(on: bool) -> None:
    """Opt span timing into device barriers at span entry/exit so the
    recorded wall-clock covers device work launched inside the span
    (off by default: the barrier serializes the pipeline it measures)."""
    _state.device_sync = bool(on)


def device_sync_enabled() -> bool:
    return _state.device_sync


def sinks_active() -> bool:
    """True when at least one event sink is attached (an open capture,
    the armed flight recorder, a workload recorder)."""
    return bool(_state._sinks)


def arrival_events_wanted() -> bool:
    """True when a sink that actually CONSUMES ``serving_request``
    arrival events is attached: a recording workload recorder or an
    open ``capture()`` window. The batcher's submit path gates event
    construction on this rather than on :func:`sinks_active` — the
    standard serving deployment keeps the flight recorder armed for
    its whole lifetime, and that sink deliberately ignores arrival
    events, so gating on "any sink" would charge every request for a
    dict nothing reads. Runs per submit: no imports, two module-int
    reads."""
    return workload.capture_active() or _capture_open()


def registry() -> Registry:
    """The process-wide metrics registry."""
    return _state.registry


def reset() -> None:
    """Clear the registry (tests; a long-lived service rotating runs)."""
    _state.registry.reset()


# -- counter convenience wrappers (no-ops when disabled) ---------------

def inc(name: str, v: float = 1.0, labels: dict | None = None) -> None:
    if _state.enabled:
        _state.registry.inc(name, v, labels)


def inc_many(items) -> None:
    """Increment several unlabeled counters in one registry lock
    round-trip (hot-path fusion; see ``Registry.inc_many``)."""
    if _state.enabled:
        _state.registry.inc_many(items)


def set_gauge(name: str, v: float, labels: dict | None = None) -> None:
    if _state.enabled:
        _state.registry.set(name, v, labels)


def observe(name: str, v: float, labels: dict | None = None,
            exemplar: str | None = None) -> None:
    if _state.enabled:
        _state.registry.observe(name, v, labels, exemplar=exemplar)


def emit_event(event: dict) -> None:
    """Deliver one raw event to every active sink (open captures, the
    armed flight recorder). The serving fault events
    (``serving_batch_error``, ``serving_overloaded``,
    ``swap_rejected``) go through here — they are flight-recorder
    triggers, not metrics. No-op (one attribute read + an empty-list
    check) when disabled or nothing is listening."""
    if _state.enabled and _state._sinks:
        import time

        event.setdefault("ts", time.time())
        _state.emit(event)


def render_prometheus(snapshot: list | None = None) -> str:
    """Prometheus text exposition of the registry (or a snapshot
    previously read back from a JSONL log's ``metrics`` event)."""
    if snapshot is None:
        snapshot = _state.registry.snapshot()
    return _render_snapshot(snapshot)


# -- fit_report integration --------------------------------------------

class FitReportView(dict):
    """``fit_report_`` as a view over the run registry: a plain dict to
    every consumer (keys are byte-identical to the historical report),
    whose numeric entries were exported to the registry as
    ``sbt_fit_<key>`` gauges at construction. Mutations after
    construction (``chunk_size_resolved`` etc.) flow back through
    ``__setitem__`` so the registry view never goes stale."""

    def __setitem__(self, key, value) -> None:
        super().__setitem__(key, value)
        if _state.enabled and isinstance(value, (int, float)) \
                and not isinstance(value, bool):
            _state.registry.set(f"sbt_fit_{key}", float(value))


def record_fit_report(report: dict) -> FitReportView:
    """Register a freshly assembled fit report with the telemetry
    subsystem and return the registry-backed view of it.

    Exports every numeric entry as an ``sbt_fit_<key>`` gauge, bumps
    the headline counters (``sbt_replicas_fitted_total``), folds
    compile/fit/h2d seconds into their log-scale histograms, and emits
    one ``fit_report`` event into any open capture.
    """
    view = FitReportView()
    if not _state.enabled:
        view.update(report)
        return view
    for k, v in report.items():
        view[k] = v  # __setitem__ exports numerics as gauges
    reg = _state.registry
    n = report.get("n_replicas") or 0
    if n:
        reg.inc("sbt_replicas_fitted_total", float(n))
    for key, metric in (
        ("compile_seconds", "sbt_compile_seconds"),
        ("fit_seconds", "sbt_fit_seconds"),
        ("h2d_seconds", "sbt_h2d_seconds"),
    ):
        val = report.get(key)
        if val is not None:
            reg.observe(metric, float(val))
    _state.emit({"kind": "fit_report", "report": dict(report)})
    return view


def __getattr__(name: str):
    if name in _SERVER_ATTRS:
        from spark_bagging_tpu.telemetry import server

        return getattr(server, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


# -- live observability plane (opt-in) ---------------------------------
# `SBT_METRICS_PORT=9100 python your_serving_script.py` is the whole
# enable story: the exposition server starts with the package and
# `curl :9100/healthz` works with zero code changes. Without the env
# var this is one dict lookup at import (server.py stays unimported).
import os as _os  # noqa: E402

if _os.environ.get("SBT_METRICS_PORT", ""):
    from spark_bagging_tpu.telemetry.server import (  # noqa: E402
        maybe_start_from_env as _maybe_start_from_env,
    )

    _maybe_start_from_env()
