"""Unified telemetry: run registry, phase spans, JSONL log, Prometheus.

The reference inherits observability from Spark — ``Instrumentation``
logging, Spark-UI stage views, metrics sinks [SURVEY §5]. This package
is the TPU-native equivalent, one subsystem with three layers:

1. **Registry** (``registry.py``) — process-wide, thread-safe counters,
   gauges, and log-scale histograms (``sbt_*`` metric names): compile
   seconds, h2d bytes, chunk latencies, replicas fitted, compile-cache
   hits/misses, prefetch stalls, checkpoint bytes, OOB evaluations,
   and the online-serving series (``sbt_serving_*``: requests, rows,
   batches, queue depth, batch fill, padding waste, compile count,
   request latency, overload rejections, swaps — serving/).
2. **Spans** (``spans.py``) — nestable phase spans
   (``with telemetry.span("compile"): ...``) recording wall-clock per
   phase; ``phase()`` composes with ``jax.named_scope`` so host spans
   and device traces share names. Device-sync timing is opt-in.
3. **Sinks** (``sinks.py``) — ``capture()`` opens a run whose events
   (spans + metric flushes) land in memory and, optionally, a
   schema-versioned JSONL file; ``render_prometheus()`` dumps the
   registry in Prometheus text format (also:
   ``python -m spark_bagging_tpu.telemetry dump``).

Cost contract: **zero overhead when disabled** — every instrumentation
site in the engines guards on :func:`enabled` (one attribute read) or
goes through :func:`span`, which returns a shared no-op context
manager when disabled. Host-side counters are ON by default (they sit
on paths that already cross the host/device boundary); the event
stream only materializes inside an open :func:`capture`.

Typical use::

    from spark_bagging_tpu import telemetry

    with telemetry.capture("telemetry.jsonl") as run:
        clf.fit(X, y)
    run.spans("compile")                 # recorded phase spans
    print(telemetry.render_prometheus())  # scrape-able metrics dump
"""

from __future__ import annotations

from spark_bagging_tpu.telemetry.registry import (
    Registry,
    render_prometheus as _render_snapshot,
)
from spark_bagging_tpu.telemetry.sinks import (
    SCHEMA_VERSION,
    Run,
    capture,
    current_run,
    last_metrics_snapshot,
    read_events,
    runs,
)
from spark_bagging_tpu.telemetry.spans import phase, span
from spark_bagging_tpu.telemetry.state import STATE as _state

__all__ = [
    "SCHEMA_VERSION", "Run", "capture", "current_run", "enabled",
    "enable", "disable", "set_device_sync", "device_sync_enabled",
    "span", "phase", "inc", "set_gauge", "observe", "registry",
    "render_prometheus", "read_events", "last_metrics_snapshot",
    "runs", "record_fit_report", "Registry", "reset",
]


def enabled() -> bool:
    """THE hot-path gate: every engine instrumentation site checks this
    (or calls :func:`span`, which does) before doing any work."""
    return _state.enabled


def enable() -> None:
    _state.enabled = True


def disable() -> None:
    """Turn all telemetry recording off (named_scope device annotations
    from :func:`phase` remain — they predate this subsystem)."""
    _state.enabled = False


def set_device_sync(on: bool) -> None:
    """Opt span timing into device barriers at span entry/exit so the
    recorded wall-clock covers device work launched inside the span
    (off by default: the barrier serializes the pipeline it measures)."""
    _state.device_sync = bool(on)


def device_sync_enabled() -> bool:
    return _state.device_sync


def registry() -> Registry:
    """The process-wide metrics registry."""
    return _state.registry


def reset() -> None:
    """Clear the registry (tests; a long-lived service rotating runs)."""
    _state.registry.reset()


# -- counter convenience wrappers (no-ops when disabled) ---------------

def inc(name: str, v: float = 1.0, labels: dict | None = None) -> None:
    if _state.enabled:
        _state.registry.inc(name, v, labels)


def set_gauge(name: str, v: float, labels: dict | None = None) -> None:
    if _state.enabled:
        _state.registry.set(name, v, labels)


def observe(name: str, v: float, labels: dict | None = None) -> None:
    if _state.enabled:
        _state.registry.observe(name, v, labels)


def render_prometheus(snapshot: list | None = None) -> str:
    """Prometheus text exposition of the registry (or a snapshot
    previously read back from a JSONL log's ``metrics`` event)."""
    if snapshot is None:
        snapshot = _state.registry.snapshot()
    return _render_snapshot(snapshot)


# -- fit_report integration --------------------------------------------

class FitReportView(dict):
    """``fit_report_`` as a view over the run registry: a plain dict to
    every consumer (keys are byte-identical to the historical report),
    whose numeric entries were exported to the registry as
    ``sbt_fit_<key>`` gauges at construction. Mutations after
    construction (``chunk_size_resolved`` etc.) flow back through
    ``__setitem__`` so the registry view never goes stale."""

    def __setitem__(self, key, value) -> None:
        super().__setitem__(key, value)
        if _state.enabled and isinstance(value, (int, float)) \
                and not isinstance(value, bool):
            _state.registry.set(f"sbt_fit_{key}", float(value))


def record_fit_report(report: dict) -> FitReportView:
    """Register a freshly assembled fit report with the telemetry
    subsystem and return the registry-backed view of it.

    Exports every numeric entry as an ``sbt_fit_<key>`` gauge, bumps
    the headline counters (``sbt_replicas_fitted_total``), folds
    compile/fit/h2d seconds into their log-scale histograms, and emits
    one ``fit_report`` event into any open capture.
    """
    view = FitReportView()
    if not _state.enabled:
        view.update(report)
        return view
    for k, v in report.items():
        view[k] = v  # __setitem__ exports numerics as gauges
    reg = _state.registry
    n = report.get("n_replicas") or 0
    if n:
        reg.inc("sbt_replicas_fitted_total", float(n))
    for key, metric in (
        ("compile_seconds", "sbt_compile_seconds"),
        ("fit_seconds", "sbt_fit_seconds"),
        ("h2d_seconds", "sbt_h2d_seconds"),
    ):
        val = report.get(key)
        if val is not None:
            reg.observe(metric, float(val))
    _state.emit({"kind": "fit_report", "report": dict(report)})
    return view
