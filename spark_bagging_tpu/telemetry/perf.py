"""Performance attribution plane — where the time and compute go.

The observability stack already says *that* serving is slow (latency
histograms, SLO gates, burn rates); this module says *where*: which
pipeline stage the milliseconds went to, what each bucket's forward
actually costs in measured seconds against its compiled FLOPs, and —
for a specific slow request — *why* (the tail explainer). Three
layers, all fed from seams that already exist:

1. **Per-stage cost accounting.** Every request trace's breakdown
   (``queue_ms``/``batch_ms``/``forward_ms``, path, bucket,
   model_version — built by the batcher, PR 5) rolls up into
   fixed-memory per-stage accumulators: ``sbt_perf_stage_seconds``
   histograms and ``sbt_perf_stage_share`` gauges labeled
   ``{stage, path[, model]}``, where the stages decompose the request
   wall-clock exactly (``queue`` + ``forward`` + ``scatter`` ==
   ``total``; scatter is the batch window minus the device forward —
   claim, packing, result delivery).
2. **A measured cost model.** Each slab forward's wall-clock joins the
   executor's compile-time ``bucket_costs`` (FLOPs / bytes from XLA's
   ``cost_analysis``, PR 6) into a live per-bucket table:
   ``sbt_perf_bucket_seconds_per_row``, achieved FLOP/s
   (``sbt_perf_bucket_achieved_flops``), and serving MFU
   (``sbt_perf_mfu``) against
   ``utils.profiling.device_peak_tflops()`` — the measured
   seconds-per-row input ROADMAP item 4's cost-driven bucket ladder
   needs (the static XLA estimates alone can't rank rungs a real
   host runs at different efficiencies).
3. **The tail explainer.** The plane retains a small deterministic
   top-K-by-duration reservoir of slow-request breakdowns;
   :func:`correlate_tail` joins each against concurrent process
   events (compiles, swaps, retries/bisects, crash-loop/degraded
   transitions, overload bursts — the flight recorder's ring) inside
   a time window and emits a deterministic per-request verdict:
   ``queue-dominated`` / ``compile-absorbed`` / ``retry-inflated`` /
   ``degraded-path`` / ``genuinely-slow-forward`` (plus ``failed``).
   Served live at ``/debug/tail``; replayed deterministically on the
   virtual clock by ``benchmarks/replay.py``'s ``attribution``
   section.

Cost contract: the plane is **opt-in** (:func:`enable`). The probes
compiled into the hot paths are the ``faults.ACTIVE`` pattern — one
module-attribute read when no plane is installed, no lock, no call —
and the breakdown probe rides the existing trace construction (no
trace, no probe). All accumulation is fixed-memory: label keys are
capped (overflow counted in ``sbt_perf_dropped_total``), the slow
reservoir is bounded, and registry exports happen every
``refresh_every`` observations, not per request.
"""

from __future__ import annotations

import time
from typing import Any, Iterable

from spark_bagging_tpu.analysis.locks import make_lock

#: the request wall-clock decomposition (exact: they sum to total_ms)
STAGES = ("queue", "forward", "scatter")

#: the tenancy journey's pre-batcher stages [ISSUE 20]: together with
#: :data:`STAGES` they tile a fleet request's wall-clock exactly
#: (admission + wfq + restore + dispatch + queue + forward + scatter
#: == total, re-based to the fleet submit instant)
JOURNEY_STAGES = ("admission", "wfq", "restore", "dispatch")

#: the tail explainer's verdict grammar, in priority order — the first
#: rule whose evidence is present wins. The tenancy rungs
#: (quarantine-shed / restore-absorbed / wfq-starved) sit above
#: queue-dominated: a tail-tenant request that waited behind a
#: heavier tenant or absorbed a cold restore must not be misfiled as
#: generic queueing [ISSUE 20]
VERDICTS = ("failed", "degraded-path", "retry-inflated",
            "compile-absorbed", "quarantine-shed", "restore-absorbed",
            "wfq-starved", "queue-dominated",
            "genuinely-slow-forward")

# event kinds (and span names) each verdict's evidence join matches
_DEGRADED_KINDS = frozenset((
    "serving_shard_failed", "serving_crash_loop",
    "serving_degraded_reject", "serving_degraded",
))
_RETRY_KINDS = frozenset((
    "serving_retry", "serving_bisect", "serving_batch_error",
))
_COMPILE_KINDS = frozenset(("serving_compile", "model_swapped",
                            "swap_failed"))
_COMPILE_SPAN_NAMES = frozenset(("serving_compile",
                                 "quality_replica_compile"))
_OVERLOAD_KINDS = frozenset(("serving_overloaded",))
# tenancy_shed events are reason-qualified at join time (kind:reason)
# so an overload shed never counts as quarantine evidence
_QUARANTINE_KINDS = frozenset(("tenant_quarantine_trip",
                               "tenancy_shed:quarantine"))
_RESTORE_KINDS = frozenset(("tenancy_restore",))


# sbt-lint: shared-state
class PerfAttribution:
    """Fixed-memory attribution accumulators for one serving process.

    ``slow_k`` bounds the top-K-by-duration breakdown reservoir the
    tail explainer reads; ``refresh_every`` is the registry-export
    cadence in observations (0 = never auto-export — the replay
    harness reads :meth:`summary` directly); ``max_keys`` caps the
    distinct ``(stage, path, model)`` label keys (overflow folds into
    ``sbt_perf_dropped_total`` rather than growing without bound).
    """

    def __init__(self, *, slow_k: int = 8, refresh_every: int = 64,
                 max_keys: int = 32) -> None:
        if slow_k < 1 or max_keys < 1:
            raise ValueError("slow_k and max_keys must be >= 1")
        if refresh_every < 0:
            raise ValueError(
                f"refresh_every must be >= 0, got {refresh_every}"
            )
        self.slow_k = int(slow_k)
        self.refresh_every = int(refresh_every)
        self.max_keys = int(max_keys)
        self._lock = make_lock("telemetry.perf")
        # (path, model) -> {"requests", "queue_s", "forward_s",
        #                   "scatter_s", "total_s"}
        self._keys: dict[tuple, dict[str, float]] = {}
        # tenant -> per-stage seconds over the FULL journey
        # (admission/wfq/restore/dispatch + queue/forward/scatter),
        # plus requests/sheds/total_s — same max_keys cap [ISSUE 20]
        self._tenants: dict[str, dict[str, float]] = {}
        self._dropped = 0
        self._dropped_exported = 0
        # bucket -> {"forwards", "rows", "seconds", "flops", "bytes"}
        # (flops/bytes are PER-FORWARD compile-time constants)
        self._buckets: dict[int, dict[str, float | None]] = {}
        self._slow: list[dict[str, Any]] = []
        self._n = 0
        self._peak_tflops: float | None = None
        self._peak_resolved = False

    # -- probes (called from the serving hot paths while installed) ----

    def observe_breakdown(self, bd: dict, *,
                          trace_id: str | None = None) -> None:
        """Fold one completed request breakdown into the stage
        rollups and the slow reservoir. Called by the batcher right
        after it finishes the breakdown — the record is exactly what
        ``future.trace.breakdown`` carries."""
        queue_s = (bd.get("queue_ms") or 0.0) / 1e3
        forward_s = (bd.get("forward_ms") or 0.0) / 1e3
        batch_s = (bd.get("batch_ms") or 0.0) / 1e3
        scatter_s = max(0.0, batch_s - forward_s)
        total_s = (bd.get("total_ms") or 0.0) / 1e3
        path = bd.get("path") or "coalesced"
        model = bd.get("model_name")
        tenant = bd.get("tenant")
        journey_s = {
            s: (bd.get(f"{s}_ms") or 0.0) / 1e3 for s in JOURNEY_STAGES
        } if tenant is not None else None
        key = (path, str(model) if model is not None else None)
        export = False
        accepted = True
        tenant_accepted = False
        with self._lock:
            acc = self._keys.get(key)
            if acc is None:
                if len(self._keys) >= self.max_keys:
                    self._dropped += 1
                    accepted = False
                else:
                    acc = self._keys[key] = {
                        "requests": 0.0, "queue_s": 0.0,
                        "forward_s": 0.0, "scatter_s": 0.0,
                        "total_s": 0.0,
                    }
            if acc is not None:
                acc["requests"] += 1
                acc["queue_s"] += queue_s
                acc["forward_s"] += forward_s
                acc["scatter_s"] += scatter_s
                acc["total_s"] += total_s
            if tenant is not None:
                tacc = self._tenants.get(tenant)
                if tacc is None:
                    if len(self._tenants) >= self.max_keys:
                        self._dropped += 1
                    else:
                        tacc = self._tenants[tenant] = {
                            "requests": 0.0, "sheds": 0.0,
                            "total_s": 0.0,
                            **{f"{s}_s": 0.0
                               for s in JOURNEY_STAGES + STAGES},
                        }
                if tacc is not None:
                    tenant_accepted = True
                    tacc["requests"] += 1
                    if bd.get("shed") is not None:
                        tacc["sheds"] += 1
                    tacc["total_s"] += total_s
                    for s, v in journey_s.items():
                        tacc[f"{s}_s"] += v
                    tacc["queue_s"] += queue_s
                    tacc["forward_s"] += forward_s
                    tacc["scatter_s"] += scatter_s
            # deterministic top-K by duration: strictly-greater evicts
            # the current minimum, ties keep the incumbent
            record = {
                "trace_id": trace_id,
                "ts": time.time(),
                "total_ms": bd.get("total_ms"),
                "queue_ms": bd.get("queue_ms"),
                "forward_ms": bd.get("forward_ms"),
                "batch_ms": bd.get("batch_ms"),
                "path": path,
                "bucket": bd.get("bucket"),
                "batch_size": bd.get("batch_size"),
                "model_name": bd.get("model_name"),
                "model_version": bd.get("model_version"),
            }
            if tenant is not None:
                # the journey fields ride into the reservoir so the
                # tail explainer can verdict wfq-starved /
                # restore-absorbed / quarantine-shed and /debug/tail
                # can filter by tenant [ISSUE 20]
                record["tenant"] = tenant
                for s in JOURNEY_STAGES:
                    record[f"{s}_ms"] = bd.get(f"{s}_ms")
                if bd.get("shed") is not None:
                    record["shed"] = bd["shed"]
            if bd.get("error") is not None:
                record["error"] = bd["error"]
            slow = self._slow
            if len(slow) < self.slow_k:
                slow.append(record)
            else:
                m = min(range(len(slow)),
                        key=lambda i: slow[i]["total_ms"] or 0.0)
                if total_s * 1e3 > (slow[m]["total_ms"] or 0.0):
                    slow[m] = record
            self._n += 1
            if self.refresh_every and self._n % self.refresh_every == 0:
                export = True
        if export:
            self.export()
        # the stage histograms export per observation (they are the
        # distribution; shares and the cost table batch on the
        # cadence) — gated by the SAME key cap as the accumulators:
        # registry series are keyed by label set, so exporting a
        # dropped key would grow the registry without bound and defeat
        # the fixed-memory contract the cap exists for
        from spark_bagging_tpu import telemetry

        if accepted and telemetry.enabled():
            labels = {"path": path}
            if model is not None:
                labels["model"] = str(model)
            for stage, v in (("queue", queue_s),
                             ("forward", forward_s),
                             ("scatter", scatter_s)):
                telemetry.observe("sbt_perf_stage_seconds", v,
                                  labels={"stage": stage, **labels},
                                  exemplar=trace_id)
        if tenant_accepted and telemetry.enabled():
            # the tenant-labeled journey twins — same series, tenant
            # dimension, full stage set (capped by the SAME max_keys
            # gate as the accumulators) [ISSUE 20]
            pairs = [(s, journey_s[s]) for s in JOURNEY_STAGES]
            pairs += [("queue", queue_s), ("forward", forward_s),
                      ("scatter", scatter_s)]
            for stage, v in pairs:
                telemetry.observe(
                    "sbt_perf_stage_seconds", v,
                    labels={"stage": stage, "tenant": tenant},
                    exemplar=trace_id)

    def observe_forward(self, bucket: int, fill: int, seconds: float,
                        cost: dict | None = None) -> None:
        """Fold one slab forward's measured wall-clock into the
        per-bucket cost model. ``cost`` is the executor's
        ``bucket_costs[bucket]`` entry (FLOPs/bytes per forward from
        ``cost_analysis`` — None values when the backend reports
        none)."""
        with self._lock:
            acc = self._buckets.get(bucket)
            if acc is None:
                if len(self._buckets) >= self.max_keys:
                    self._dropped += 1
                    return
                acc = self._buckets[bucket] = {
                    "forwards": 0.0, "rows": 0.0, "seconds": 0.0,
                    "flops": None, "bytes": None,
                }
            acc["forwards"] += 1
            acc["rows"] += fill
            acc["seconds"] += seconds
            if cost:
                if cost.get("flops") is not None:
                    acc["flops"] = float(cost["flops"])
                if cost.get("bytes") is not None:
                    acc["bytes"] = float(cost["bytes"])

    # -- views ---------------------------------------------------------

    def _peak(self) -> float | None:
        """Device peak TFLOP/s, resolved once (it queries jax)."""
        if not self._peak_resolved:
            from spark_bagging_tpu.utils.profiling import (
                device_peak_tflops,
            )

            # sbt-lint: disable=shared-state-unlocked — idempotent lazy resolve; racing writers compute the same value
            self._peak_tflops = device_peak_tflops()
            # sbt-lint: disable=shared-state-unlocked — same benign idempotent write
            self._peak_resolved = True
        return self._peak_tflops

    def cost_model(self) -> dict[str, dict[str, float | None]]:
        """The live per-bucket cost table: measured seconds-per-row,
        achieved FLOP/s, and MFU against the device bf16 peak (None
        when the device kind is unknown — CPU — or the backend
        reported no FLOPs)."""
        peak = self._peak()
        with self._lock:
            buckets = {b: dict(acc) for b, acc in self._buckets.items()}
        out: dict[str, dict[str, float | None]] = {}
        for b in sorted(buckets):
            acc = buckets[b]
            seconds, rows = acc["seconds"], acc["rows"]
            flops = acc["flops"]
            achieved = (flops * acc["forwards"] / seconds
                        if flops and seconds > 0 else None)
            out[str(b)] = {
                "forwards": int(acc["forwards"]),
                "rows": int(rows),
                "seconds": round(seconds, 6),
                "flops_per_forward": flops,
                "bytes_per_forward": acc["bytes"],
                "seconds_per_row": (seconds / rows if rows else None),
                "achieved_flops": achieved,
                "mfu": (achieved / (peak * 1e12)
                        if achieved is not None and peak else None),
            }
        return out

    def summary(self) -> dict[str, Any]:
        """One JSON-friendly view of the whole plane: overall and
        per-(path, model) stage totals + shares, the cost-model table,
        MFU, and the slow reservoir."""
        with self._lock:
            keys = {k: dict(v) for k, v in self._keys.items()}
            tenants = {t: dict(v) for t, v in self._tenants.items()}
            n = self._n
            dropped = self._dropped
        stages_total = {s: 0.0 for s in STAGES}
        total_s = 0.0
        by_key = []
        for (path, model), acc in sorted(keys.items(),
                                         key=lambda kv: str(kv[0])):
            for s in STAGES:
                stages_total[s] += acc[f"{s}_s"]
            total_s += acc["total_s"]
            entry = {
                "path": path, "model": model,
                "requests": int(acc["requests"]),
                "stages": _shares(acc),
            }
            by_key.append(entry)
        cost = self.cost_model()
        peak = self._peak()
        # overall achieved FLOP/s: total flops dispatched over total
        # measured forward seconds (the time-weighted mean, not a mean
        # of per-bucket rates)
        flops_total = sum(
            (c["flops_per_forward"] or 0.0) * c["forwards"]
            for c in cost.values()
        )
        sec_total = sum(c["seconds"] for c in cost.values())
        overall = (flops_total / sec_total
                   if sec_total > 0 and flops_total > 0 else None)
        return {
            "requests": int(n),
            "dropped_keys": int(dropped),
            "stages": {
                s: {
                    "seconds": round(stages_total[s], 6),
                    "share": (stages_total[s] / total_s
                              if total_s > 0 else None),
                }
                for s in STAGES
            },
            "by_key": by_key,
            "tenants": {
                t: {
                    "requests": int(acc["requests"]),
                    "sheds": int(acc["sheds"]),
                    "stages": _journey_shares(acc),
                }
                for t, acc in sorted(tenants.items())
            },
            "cost_model": cost,
            "achieved_flops": overall,
            "peak_tflops_bf16": peak,
            "mfu": (overall / (peak * 1e12)
                    if overall is not None and peak else None),
            "slow": self.slow_records(),
        }

    def slow_records(self, limit: int | None = None) -> list[dict]:
        """The retained slowest breakdowns, slowest first."""
        with self._lock:
            out = sorted(self._slow,
                         key=lambda r: -(r["total_ms"] or 0.0))
        return [dict(r) for r in (out[:limit] if limit else out)]

    def export(self) -> None:
        """Push the share gauges and cost-model gauges to the metrics
        registry (called on the ``refresh_every`` cadence and by the
        ``/debug/tail`` scrape)."""
        from spark_bagging_tpu import telemetry

        if not telemetry.enabled():
            return
        with self._lock:
            keys = {k: dict(v) for k, v in self._keys.items()}
            tenants = {t: dict(v) for t, v in self._tenants.items()}
            dropped_delta = self._dropped - self._dropped_exported
            self._dropped_exported = self._dropped
        for (path, model), acc in keys.items():
            labels = {"path": path}
            if model is not None:
                labels["model"] = model
            for stage, share in _shares(acc).items():
                if share["share"] is not None:
                    telemetry.set_gauge(
                        "sbt_perf_stage_share", share["share"],
                        labels={"stage": stage, **labels},
                    )
        for tenant, acc in tenants.items():
            for stage, share in _journey_shares(acc).items():
                if share["share"] is not None:
                    telemetry.set_gauge(
                        "sbt_perf_stage_share", share["share"],
                        labels={"stage": stage, "tenant": tenant},
                    )
        if dropped_delta > 0:
            telemetry.inc("sbt_perf_dropped_total", dropped_delta)
        cost = self.cost_model()
        for b, c in cost.items():
            labels = {"bucket": b}
            if c["seconds_per_row"] is not None:
                telemetry.set_gauge("sbt_perf_bucket_seconds_per_row",
                                    c["seconds_per_row"], labels=labels)
            if c["achieved_flops"] is not None:
                telemetry.set_gauge("sbt_perf_bucket_achieved_flops",
                                    c["achieved_flops"], labels=labels)
        peak = self._peak()
        flops_total = sum(
            (c["flops_per_forward"] or 0.0) * c["forwards"]
            for c in cost.values()
        )
        sec_total = sum(c["seconds"] for c in cost.values())
        if peak and sec_total > 0 and flops_total > 0:
            telemetry.set_gauge(
                "sbt_perf_mfu", flops_total / sec_total / (peak * 1e12)
            )


def _shares(acc: dict[str, float]) -> dict[str, dict]:
    total = acc["total_s"]
    return {
        s: {
            "seconds": round(acc[f"{s}_s"], 6),
            "share": (acc[f"{s}_s"] / total if total > 0 else None),
        }
        for s in STAGES
    }


def _journey_shares(acc: dict[str, float]) -> dict[str, dict]:
    """Per-stage seconds + shares over the FULL tenancy journey
    (pre-batcher stages included) — the tenant twin of
    :func:`_shares`."""
    total = acc["total_s"]
    return {
        s: {
            "seconds": round(acc[f"{s}_s"], 6),
            "share": (acc[f"{s}_s"] / total if total > 0 else None),
        }
        for s in JOURNEY_STAGES + STAGES
    }


# -- the tail explainer ------------------------------------------------

def correlate_tail(
    records: Iterable[dict],
    events: Iterable[dict],
    *,
    window_s: float = 1.0,
    queue_frac: float = 0.5,
    queue_threshold_ms: float | None = None,
    clock_key: str = "ts",
) -> list[dict]:
    """Explain each slow-request record by joining it against the
    concurrent process events, emitting a deterministic verdict.

    ``records`` carry at least a timestamp under ``clock_key`` plus
    (when known) the breakdown fields (``total_ms``/``queue_ms``/
    ``error``...). ``events`` are process events — the flight
    recorder's ring in production, counter-delta-synthesized virtual
    events in replay — matched when their ``clock_key`` (falling back
    to ``ts``) lies within ``window_s`` of the record's.

    The verdict is the FIRST rule in priority order whose evidence is
    present (every matched factor is still listed):

    1. ``failed`` — the record carries an error;
    2. ``degraded-path`` — shard loss / crash loop / degraded
       transitions in the window (or the record says ``degraded``);
    3. ``retry-inflated`` — retries, bisects, or batch errors in the
       window;
    4. ``compile-absorbed`` — a serving compile (or a swap, whose warm
       pre-compiles are the usual carrier) in the window;
    5. ``quarantine-shed`` — the record IS a quarantine shed (its
       ``shed`` field says so) or a quarantine trip / quarantine shed
       event lands in the window [ISSUE 20];
    6. ``restore-absorbed`` — the record carries ``restore_ms > 0``
       (it paid a cold tenant's AOT restore) or a ``tenancy_restore``
       event for its window [ISSUE 20];
    7. ``wfq-starved`` — fair-queue wait over ``queue_frac`` of the
       total (or over ``queue_threshold_ms`` when the total is
       unknown): the request waited behind heavier tenants, not
       behind its own batcher [ISSUE 20];
    8. ``queue-dominated`` — queue wait over ``queue_frac`` of the
       total (or over ``queue_threshold_ms`` when the total is
       unknown — the replay harness passes the coalescing window's
       half, making the verdict a pure function of the schedule);
    9. ``genuinely-slow-forward`` — none of the above: the device
       forward itself was the time.
    """
    evs = []
    for e in events:
        t = e.get(clock_key)
        if t is None:
            t = e.get("ts")
        if t is None:
            continue
        kind = e.get("kind")
        if kind == "span":
            if e.get("name") not in _COMPILE_SPAN_NAMES:
                continue
            kind = "serving_compile"
        elif kind == "tenancy_shed":
            # reason-qualified so only quarantine sheds count as
            # quarantine evidence (an overload shed is queue weather)
            kind = f"tenancy_shed:{e.get('reason')}"
        evs.append((float(t), kind))
    evs.sort()
    out = []
    for r in records:
        t = r.get(clock_key)
        if t is None:
            t = r.get("ts")
        nearby: list[tuple[float, str]] = []
        if t is not None:
            lo, hi = float(t) - window_s, float(t) + window_s
            nearby = [(et, k) for et, k in evs if lo <= et <= hi]
        factors = []
        kinds = {k for _, k in nearby}
        if r.get("error") is not None:
            factors.append("error")
        if kinds & _DEGRADED_KINDS or r.get("degraded"):
            factors.append("degraded")
        if kinds & _RETRY_KINDS:
            factors.append("retries")
        if kinds & _COMPILE_KINDS:
            factors.append("compiles")
        if kinds & _OVERLOAD_KINDS:
            factors.append("overload-burst")
        if (r.get("shed") == "quarantine"
                or kinds & _QUARANTINE_KINDS):
            factors.append("quarantine")
        if ((r.get("restore_ms") or 0.0) > 0
                or kinds & _RESTORE_KINDS):
            factors.append("restore")
        queue_ms = r.get("queue_ms")
        total_ms = r.get("total_ms")
        wfq_ms = r.get("wfq_ms")
        queue_heavy = False
        if queue_ms is not None:
            if total_ms:
                queue_heavy = queue_ms / total_ms >= queue_frac
            elif queue_threshold_ms is not None:
                queue_heavy = queue_ms >= queue_threshold_ms
        wfq_heavy = False
        if wfq_ms is not None:
            if total_ms:
                wfq_heavy = wfq_ms / total_ms >= queue_frac
            elif queue_threshold_ms is not None:
                wfq_heavy = wfq_ms >= queue_threshold_ms
        if wfq_heavy:
            factors.append("wfq")
        if queue_heavy or "overload-burst" in factors:
            factors.append("queue")
        if "error" in factors:
            verdict = "failed"
        elif "degraded" in factors:
            verdict = "degraded-path"
        elif "retries" in factors:
            verdict = "retry-inflated"
        elif "compiles" in factors:
            verdict = "compile-absorbed"
        elif "quarantine" in factors:
            verdict = "quarantine-shed"
        elif "restore" in factors:
            verdict = "restore-absorbed"
        elif "wfq" in factors:
            verdict = "wfq-starved"
        elif "queue" in factors:
            verdict = "queue-dominated"
        else:
            verdict = "genuinely-slow-forward"
        entry = {
            "verdict": verdict,
            "factors": factors,
            "events_in_window": len(nearby),
            "evidence": [
                {"t": et, "kind": k} for et, k in nearby[:8]
            ],
        }
        for k in ("trace_id", "idx", "total_ms", "queue_ms",
                  "forward_ms", "path", "bucket", "batch_size",
                  "error", "tenant", "admission_ms", "wfq_ms",
                  "restore_ms", "dispatch_ms", "shed"):
            if r.get(k) is not None:
                entry[k] = r[k]
        if t is not None:
            entry["t"] = float(t)
        out.append(entry)
    return out


def tail_report(*, limit: int = 8, window_s: float = 1.0,
                tenant: str | None = None) -> dict:
    """The ``/debug/tail`` body: the slowest retained requests (the
    perf plane's reservoir when installed, else the latency
    histogram's exemplars + top-K reservoir) each explained against
    the flight recorder's event ring. ``tenant`` filters to one
    tenant's records (``/debug/tail?tenant=``) — fleet records carry
    the tenant on the breakdown, so the tail forensics answer "why is
    THIS tenant slow" directly [ISSUE 20]."""
    from spark_bagging_tpu import telemetry
    from spark_bagging_tpu.telemetry import recorder

    plane = ACTIVE
    source = "perf-reservoir"
    records = plane.slow_records() if plane is not None else []
    if not records:
        source = "latency-exemplars"
        records = _exemplar_records(limit)
    if tenant is not None:
        records = [r for r in records if r.get("tenant") == tenant]
    records = records[:limit]
    rec = recorder.get()
    events = rec.events() if rec is not None else []
    tail = correlate_tail(records, events, window_s=window_s)
    tail.sort(key=lambda r: -(r.get("total_ms") or 0.0))
    out = {
        "source": source,
        "window_s": window_s,
        "tenant": tenant,
        "perf_plane_active": plane is not None,
        "flight_recorder_armed": rec is not None and rec.armed,
        "tail": tail,
    }
    if plane is not None:
        plane.export()
        summary = plane.summary()
        out["stages"] = summary["stages"]
        if tenant is not None:
            out["tenant_stages"] = summary["tenants"].get(tenant)
    if not tail:
        out["note"] = (
            "no slow-request records retained yet; enable the perf "
            "plane (telemetry.perf.enable()) and serve traffic, or "
            "wait for latency exemplars"
        )
    return out


def _exemplar_records(limit: int) -> list[dict]:
    """Fallback tail records off the request-latency histogram's
    exemplars (newest per bucket) and top-K-by-duration reservoir —
    trace id + latency only (no breakdown), which still supports the
    event-join verdicts."""
    from spark_bagging_tpu import telemetry

    h = telemetry.registry().peek("sbt_serving_latency_seconds")
    if h is None or h.kind != "histogram":
        return []
    seen: dict[str, dict] = {}
    pool = list(h.exemplars.values()) + list(h.slow_exemplars)
    for ex in pool:
        tid = ex.get("trace_id")
        if tid is None:
            continue
        cur = seen.get(tid)
        if cur is None or (ex.get("value") or 0) > (cur.get("value") or 0):
            seen[tid] = ex
    records = [
        {
            "trace_id": tid,
            "ts": ex.get("ts"),
            "total_ms": ((ex.get("value") or 0.0) * 1e3) or None,
        }
        for tid, ex in seen.items()
    ]
    records.sort(key=lambda r: -(r["total_ms"] or 0.0))
    return records[:limit]


# -- process default ---------------------------------------------------

#: the probe target: serving hot paths read this ONE module attribute
#: (the ``faults.ACTIVE`` pattern) — None means the plane is off and
#: the probe cost is a single attribute read
ACTIVE: "PerfAttribution | None" = None

_default_lock = make_lock("telemetry.perf.default")


def enable(**kwargs: Any) -> PerfAttribution:
    """Install a fresh :class:`PerfAttribution` as the process plane
    (``kwargs`` are its constructor options). A second enable starts a
    new measurement window — the old plane's accumulators are simply
    no longer fed."""
    global ACTIVE
    plane = PerfAttribution(**kwargs)
    with _default_lock:
        ACTIVE = plane
    return plane


def disable() -> None:
    """Uninstall the process plane (probes go back to one attribute
    read; accumulated state on the old plane stays readable)."""
    global ACTIVE
    with _default_lock:
        ACTIVE = None


def install(plane: "PerfAttribution | None") -> "PerfAttribution | None":
    """Install ``plane`` (or None) as the probe target, returning the
    previous one — the replay harness's save/restore seam."""
    global ACTIVE
    with _default_lock:
        prev = ACTIVE
        ACTIVE = plane
    return prev


def get() -> "PerfAttribution | None":
    """The installed plane, or None."""
    return ACTIVE
