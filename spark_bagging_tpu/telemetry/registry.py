"""Process-wide metrics registry: counters, gauges, log-scale histograms.

The reference gets metric plumbing for free from Spark's
``Instrumentation`` + metrics sinks [SURVEY §5]; here one thread-safe
registry holds every counter/gauge/histogram the engines emit
(compile seconds, h2d bytes, chunk latencies, replicas fitted,
compile-cache hits/misses, prefetch stalls, checkpoint bytes, OOB
evaluations), keyed by ``(name, sorted labels)``. Metric names follow
the Prometheus convention with the ``sbt_`` (spark-bagging-tpu) prefix;
:func:`render_prometheus` emits the text exposition format so the
registry can be scraped or diffed with standard tooling.

Thread-safety: engines emit from the fit thread, the prefetch producer
thread, and jax's compilation-cache listener callbacks concurrently —
every mutation and snapshot takes the registry lock. The hot-path
cheapness contract lives one level up (``telemetry.enabled()`` gates
every call site), not here.
"""

from __future__ import annotations

import math
from typing import Any, Iterable

from spark_bagging_tpu.analysis.locks import make_lock

# Log-scale histogram bounds: decades from 100 microseconds to 1000
# seconds cover every latency this stack records (a chunk step is
# ~1e-3..1e0 s, a headline compile ~1e0..1e2 s); byte-valued
# histograms reuse the same grid scaled by _BYTES_SCALE.
DEFAULT_BUCKETS: tuple[float, ...] = tuple(
    10.0 ** e for e in range(-4, 4)
) + (math.inf,)


def _label_key(labels: dict[str, Any] | None) -> tuple:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonically increasing value (Prometheus ``counter``)."""

    kind = "counter"

    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError(f"counter increment must be >= 0, got {v}")
        self.value += v


class Gauge:
    """Last-write-wins value (Prometheus ``gauge``)."""

    kind = "gauge"

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, v: float = 1.0) -> None:
        self.value += v


class Histogram:
    """Log-scale bucketed distribution (Prometheus ``histogram``).

    Buckets store per-bucket counts; cumulative ``le`` counts are
    produced at render time (the exposition format's convention).
    """

    kind = "histogram"

    def __init__(self, buckets: Iterable[float] = DEFAULT_BUCKETS) -> None:
        self.bounds = tuple(sorted(buckets))
        if not self.bounds or self.bounds[-1] != math.inf:
            self.bounds = self.bounds + (math.inf,)
        self.counts = [0] * len(self.bounds)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        self.sum += v
        self.count += 1
        for i, b in enumerate(self.bounds):
            if v <= b:
                self.counts[i] += 1
                return


# sbt-lint: shared-state
class Registry:
    """Thread-safe metric store keyed by ``(name, labels)``."""

    def __init__(self) -> None:
        self._lock = make_lock("telemetry.registry")
        self._metrics: dict[tuple[str, tuple], Any] = {}

    def _get_locked(self, name: str, labels, cls):
        """Fetch-or-create under the ALREADY-HELD lock."""
        key = (name, _label_key(labels))
        m = self._metrics.get(key)
        if m is None:
            # sbt-lint: disable=shared-state-unlocked — every caller holds self._lock (enforced by the _locked naming convention)
            m = self._metrics[key] = cls()
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}"
            )
        return m

    def counter(self, name: str, labels: dict | None = None) -> Counter:
        with self._lock:
            return self._get_locked(name, labels, Counter)

    def gauge(self, name: str, labels: dict | None = None) -> Gauge:
        with self._lock:
            return self._get_locked(name, labels, Gauge)

    def histogram(self, name: str, labels: dict | None = None) -> Histogram:
        with self._lock:
            return self._get_locked(name, labels, Histogram)

    # convenience mutators (one lock round-trip each; call sites stay
    # one-liners behind the enabled() gate)

    def inc(self, name: str, v: float = 1.0, labels: dict | None = None) -> None:
        with self._lock:
            self._get_locked(name, labels, Counter).inc(v)

    def set(self, name: str, v: float, labels: dict | None = None) -> None:
        with self._lock:
            self._get_locked(name, labels, Gauge).set(v)

    def observe(self, name: str, v: float, labels: dict | None = None) -> None:
        with self._lock:
            self._get_locked(name, labels, Histogram).observe(v)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()

    def snapshot(self) -> list[dict]:
        """JSON-serializable dump of every metric (the ``metrics``
        JSONL event body, and the input to :func:`render_prometheus`)."""
        out = []
        with self._lock:
            for (name, labels), m in sorted(self._metrics.items()):
                entry: dict[str, Any] = {
                    "name": name,
                    "kind": m.kind,
                    "labels": dict(labels),
                }
                if m.kind == "histogram":
                    entry["buckets"] = [
                        ["+Inf" if b == math.inf else b, c]
                        for b, c in zip(m.bounds, m.counts)
                    ]
                    entry["sum"] = m.sum
                    entry["count"] = m.count
                else:
                    entry["value"] = m.value
                out.append(entry)
        return out


def _fmt_labels(labels: dict, extra: dict | None = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    body = ",".join(f'{k}="{v}"' for k, v in sorted(merged.items()))
    return "{" + body + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    # non-finite first: int(NaN)/int(inf) raise, and a diverged fit's
    # loss_mean=NaN must not take the instrument panel down with it
    # (Prometheus text spec spells these NaN/+Inf/-Inf)
    if not math.isfinite(f):
        return "NaN" if math.isnan(f) else ("+Inf" if f > 0 else "-Inf")
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_prometheus(snapshot: list[dict]) -> str:
    """Prometheus text exposition of a :meth:`Registry.snapshot`."""
    lines: list[str] = []
    seen_type: set[str] = set()
    for entry in snapshot:
        name, kind, labels = entry["name"], entry["kind"], entry["labels"]
        if name not in seen_type:
            lines.append(f"# TYPE {name} {kind}")
            seen_type.add(name)
        if kind == "histogram":
            cum = 0
            for le, c in entry["buckets"]:
                cum += c
                lines.append(
                    f"{name}_bucket"
                    f"{_fmt_labels(labels, {'le': le})} {cum}"
                )
            lines.append(
                f"{name}_sum{_fmt_labels(labels)} "
                f"{_fmt_value(entry['sum'])}"
            )
            lines.append(
                f"{name}_count{_fmt_labels(labels)} {entry['count']}"
            )
        else:
            lines.append(
                f"{name}{_fmt_labels(labels)} {_fmt_value(entry['value'])}"
            )
    return "\n".join(lines) + ("\n" if lines else "")
