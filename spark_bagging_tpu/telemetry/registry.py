"""Process-wide metrics registry: counters, gauges, log-scale histograms.

The reference gets metric plumbing for free from Spark's
``Instrumentation`` + metrics sinks [SURVEY §5]; here one thread-safe
registry holds every counter/gauge/histogram the engines emit
(compile seconds, h2d bytes, chunk latencies, replicas fitted,
compile-cache hits/misses, prefetch stalls, checkpoint bytes, OOB
evaluations), keyed by ``(name, sorted labels)``. Metric names follow
the Prometheus convention with the ``sbt_`` (spark-bagging-tpu) prefix;
:func:`render_prometheus` emits the text exposition format so the
registry can be scraped or diffed with standard tooling.

Thread-safety: engines emit from the fit thread, the prefetch producer
thread, and jax's compilation-cache listener callbacks concurrently —
every mutation and snapshot takes the registry lock. The hot-path
cheapness contract lives one level up (``telemetry.enabled()`` gates
every call site), not here.
"""

from __future__ import annotations

import math
import time
from typing import Any, Iterable

from spark_bagging_tpu.analysis.locks import make_lock

# Log-scale histogram bounds: decades from 100 microseconds to 1000
# seconds cover every latency this stack records (a chunk step is
# ~1e-3..1e0 s, a headline compile ~1e0..1e2 s); byte-valued
# histograms reuse the same grid scaled by _BYTES_SCALE.
DEFAULT_BUCKETS: tuple[float, ...] = tuple(
    10.0 ** e for e in range(-4, 4)
) + (math.inf,)

# Central help-text table for every stable sbt_* series — the single
# source `render_prometheus` emits `# HELP` lines from, and the
# documentation a scraper's UI shows next to the graph. Dynamic series
# (the per-fit-report `sbt_fit_<key>` gauges) are covered by prefix in
# `_help_for`. Keep entries one line: the exposition format forbids
# raw newlines in HELP text (escaped ones are legal but unreadable).
SERIES_HELP: dict[str, str] = {
    "sbt_replicas_fitted_total": "Base replicas fitted across all fit calls",
    "sbt_compile_seconds": "XLA compile wall-clock per fit (histogram)",
    "sbt_fit_seconds": "Device fit wall-clock per fit call (histogram)",
    "sbt_h2d_seconds": "Host-to-device transfer seconds per fit (histogram)",
    "sbt_h2d_bytes_total": "Bytes transferred host-to-device",
    "sbt_d2h_bytes_total": "Bytes transferred device-to-host",
    "sbt_oob_evaluations_total": "Out-of-bag scoring passes",
    "sbt_collective_seconds": "Multihost collective wall-clock (histogram)",
    "sbt_stream_epochs_total": "Streaming-fit epochs completed",
    "sbt_stream_chunks_total": "Streaming-fit chunks consumed",
    "sbt_chunks_yielded_total": "Chunks yielded by streaming sources",
    "sbt_chunk_seconds": "Per-chunk step wall-clock (histogram)",
    "sbt_prefetch_queue_depth": "Prefetch queue depth (gauge)",
    "sbt_prefetch_stall_seconds_total": "Seconds the consumer stalled on prefetch",
    "sbt_checkpoint_bytes_total": "Checkpoint bytes written",
    "sbt_checkpoint_seconds": "Checkpoint save wall-clock (histogram)",
    "sbt_compile_cache_hits_total": "Persistent compile-cache hits",
    "sbt_compile_cache_misses_total": "Persistent compile-cache misses",
    "sbt_shardmap_traces_total": "shard_map traced executions",
    "sbt_serving_requests_total": "Requests admitted by MicroBatcher.submit()",
    "sbt_serving_rows_total": "Rows served through the executor forward",
    "sbt_serving_batches_total": "Coalesced micro-batches forwarded",
    "sbt_serving_queue_depth": "Requests admitted but not yet forwarded (gauge)",
    "sbt_serving_batch_fill_ratio": "Real rows / bucket rows per forward (histogram)",
    "sbt_serving_padding_rows_total": "Padding rows added to reach bucket shapes",
    "sbt_serving_compiles_total": "Serving bucket compiles (zero after warmup)",
    "sbt_serving_compile_seconds": "Serving bucket compile wall-clock (histogram)",
    "sbt_serving_latency_seconds": "Request latency submit-to-result (histogram; optional path label: direct/coalesced)",
    "sbt_serving_direct_dispatch_total": "Requests served inline by adaptive direct dispatch (idle fast path)",
    "sbt_serving_coalesced_total": "Requests served via the coalescing worker path",
    "sbt_serving_shard_forwards_total": "Slab forwards executed by the replica-sharded (mesh) serving program",
    "sbt_serving_shard_devices": "Replica-axis size of the serving mesh (gauge, set at sharded-executor construction)",
    "sbt_program_cache_hits_total": "Unified compiled-program cache hits (a compile someone already paid, reused)",
    "sbt_program_cache_misses_total": "Unified compiled-program cache lookups that found nothing",
    "sbt_program_cache_evictions_total": "Programs LRU-evicted from the unified compiled-program cache",
    "sbt_program_cache_entries": "Programs resident in the unified compiled-program cache (gauge)",
    "sbt_serving_aot_saved_total": "Compiled bucket executables persisted to an AOT cache",
    "sbt_serving_aot_restored_total": "Bucket executables hydrated from a persisted AOT cache (no compile)",
    "sbt_serving_aot_misses_total": "AOT cache lookups that fell back to lowering (absent/key-mismatched/unreadable)",
    "sbt_serving_overloaded_total": "Requests shed with Overloaded backpressure",
    "sbt_serving_shed_total": "Requests shed at the serving edge (label reason: overload/deadline/degraded)",
    "sbt_serving_retries_total": "Transient micro-batch forward failures retried with backoff",
    "sbt_serving_batch_bisects_total": "Failing coalesced batches split in half to isolate a poisoned request",
    "sbt_serving_request_failures_total": "Requests failed by a forward error after retries and bisect isolation",
    "sbt_serving_worker_crashes_total": "Batcher worker crashes caught by the supervisor",
    "sbt_serving_worker_restarts_total": "Fresh batcher worker threads started by the supervisor (or revive())",
    "sbt_serving_crash_loops_total": "Crash-loop detections that put a batcher into degraded reject mode",
    "sbt_serving_shard_failures_total": "Mesh serving shards marked failed and dropped from the quorum",
    "sbt_serving_degraded": "Executor serves a degraded surviving-replica aggregate (gauge, 0/1)",
    "sbt_serving_degraded_replicas": "Replicas the degraded aggregate averages over (gauge; 0 when healthy)",
    "sbt_serving_degraded_forwards_total": "Slab forwards served by a degraded surviving-subset program",
    "sbt_serving_degraded_compiles_total": "Degraded-program bucket compiles (fault response, not serving compiles)",
    "sbt_serving_swap_failed_total": "Hot swaps that died building the replacement and rolled back (live executor unchanged)",
    "sbt_faults_armed": "A deterministic fault-injection plan is armed in this process (gauge, 0/1)",
    "sbt_faults_injected_total": "Faults fired by the armed injection plan (labels site, action)",
    "sbt_serving_models_registered_total": "Models registered for serving",
    "sbt_serving_swaps_total": "Successful hot swaps",
    "sbt_serving_swap_rejected_total": "Hot swaps rejected by contract validation",
    "sbt_serving_model_version": "Live model version per registered name (gauge)",
    "sbt_serving_batch_errors_total": "Micro-batches failed by an executor error",
    "sbt_serving_bucket_cost_flops": "Compiled FLOPs per forward at this bucket (gauge, label bucket)",
    "sbt_serving_bucket_cost_bytes": "Compiled bytes accessed per forward at this bucket (gauge, label bucket)",
    "sbt_serving_flops_total": "FLOPs dispatched by serving forwards (cost-analysis attributed)",
    "sbt_serving_padding_flops_total": "FLOPs spent on padding rows (waste, cost-analysis attributed)",
    "sbt_quality_rows_total": "Rows folded into the quality plane's live sketches",
    "sbt_quality_psi_max": "Max per-feature PSI of live traffic vs the training reference (gauge)",
    "sbt_quality_psi_mean": "Mean per-feature PSI vs the training reference (gauge)",
    "sbt_quality_ks_max": "Max per-feature binned KS statistic vs the training reference (gauge)",
    "sbt_quality_feature_psi": "Per-feature PSI vs the training reference (gauge, label feature)",
    "sbt_quality_feature_ks": "Per-feature binned KS vs the training reference (gauge, label feature)",
    "sbt_quality_prediction_psi": "PSI of served prediction distribution vs the training reference (gauge)",
    "sbt_quality_confidence_psi": "PSI of served confidence vs the OOB reference (gauge)",
    "sbt_quality_confidence_p50": "P2-sketched median served confidence (gauge)",
    "sbt_quality_refresh_total": "Drift recomputations + gauge exports by quality monitors",
    "sbt_quality_disagreement": "Ensemble disagreement per sampled batch (histogram)",
    "sbt_quality_disagreement_mean": "Running mean ensemble disagreement across sampled batches (gauge)",
    "sbt_quality_disagreement_samples_total": "Batches sampled through the per-replica disagreement tap",
    "sbt_quality_disagreement_compiles_total": "Per-replica tap forwards compiled (separate from serving compiles)",
    "sbt_alerts_fired_total": "Alert rule activations (label rule)",
    "sbt_alerts_resolved_total": "Alert rule resolutions (label rule)",
    "sbt_alerts_suppressed_total": "Alert re-fires suppressed by per-rule cooldown (label rule)",
    "sbt_alerts_evaluations_total": "Alert engine evaluation passes",
    "sbt_alerts_active": "Alert rules currently active (gauge)",
    "sbt_flight_dumps_total": "Flight-recorder dumps written",
    "sbt_flight_dumps_suppressed_total": "Flight-recorder dumps suppressed by cooldown",
    "sbt_process_uptime_seconds": "Seconds since the exposition server started (gauge)",
    "sbt_process_rss_bytes": "Resident set size of this process (gauge, sampled at scrape)",
    "sbt_fleet_peers": "Peer processes configured on the fleet aggregator (gauge)",
    "sbt_fleet_peers_fresh": "Peers whose latest scrape succeeded and is within the staleness bound (gauge)",
    "sbt_fleet_peers_stale": "Peers excluded from the merge/quorum: failed or overdue last scrape (gauge)",
    "sbt_fleet_quorum": "Fleet quorum health: 1 healthy, 0 lost (gauge; degraded still counts 1)",
    "sbt_fleet_scrapes_total": "Peer scrape attempts by the fleet aggregator",
    "sbt_fleet_scrape_failures_total": "Peer scrapes that failed (timeout/HTTP error; label process)",
    "sbt_fleet_scrape_age_seconds": "Seconds since the last successful scrape of a peer (gauge, label process)",
    "sbt_fleet_merged_series": "Peer-derived series in the latest merge, before the fleet-synthesized sbt_fleet_* series are appended (gauge)",
    "sbt_fleet_merge_conflicts_total": "Series dropped from a merge because peers disagree on kind or histogram bounds",
    "sbt_fleet_version": "Live model version reported by one peer (gauge, labels model+process)",
    "sbt_fleet_version_skew": "Max minus min live model version across fresh peers (gauge, label model; 0 = converged)",
    "sbt_fleet_convergence_seconds": "Rolling-swap convergence time: version skew rising above 0 until back to 0 (histogram, label model)",
    "sbt_perf_stage_seconds": "Per-request wall-clock attributed to one pipeline stage (histogram, labels stage + path, or stage + tenant over the full journey: admission/wfq/restore/dispatch/queue/forward/scatter)",
    "sbt_perf_stage_share": "Share of total request wall-clock spent in one stage (gauge, labels stage + path, or stage + tenant for the journey twin)",
    "sbt_perf_bucket_seconds_per_row": "Measured forward seconds per served row at this bucket (gauge, label bucket — the live cost model)",
    "sbt_perf_bucket_achieved_flops": "Achieved FLOP/s of this bucket's forward: compiled FLOPs over measured seconds (gauge, label bucket)",
    "sbt_perf_mfu": "Serving model-FLOPs utilization: achieved FLOP/s over the device bf16 peak (gauge; absent on unknown device kinds)",
    "sbt_perf_dropped_total": "Perf-attribution observations dropped by the fixed-memory key cap",
    "sbt_profile_captures_total": "On-demand jax.profiler captures started (/debug/profile, trace(), the CLI)",
    "sbt_profile_rejected_total": "Profile captures rejected by the single-flight guard (one capture per process)",
    "sbt_profile_active": "A device-profile capture is currently running (gauge, 0/1)",
    "sbt_scenario_runs_total": "Registered verification scenarios executed (benchmarks/scenarios; label scenario)",
    "sbt_scenario_failures_total": "Scenario conformance failures by class (labels scenario + kind=digest/slo/baseline-missing)",
    "sbt_scenario_digest_match": "Latest scenario digest verdict vs its committed baseline (gauge, label scenario; 1 match / 0 mismatch)",
    "sbt_scenario_wall_seconds": "Wall-clock of the latest run of one scenario, repeats included (gauge, label scenario)",
    "sbt_online_updates_total": "Streaming partial_fit steps applied by online updaters (label model when attached)",
    "sbt_online_examples_total": "Rows consumed by streaming online updates",
    "sbt_online_oob_rows_total": "Rows scored by the streaming out-of-bag quality tap (Poisson draw 0 replicas)",
    "sbt_online_oob_estimate": "Running streaming OOB quality estimate: accuracy or R2 over OOB-voted rows (gauge)",
    "sbt_online_refits_triggered_total": "Drift-alert refit triggers accepted by the online trainer (label model)",
    "sbt_online_refits_published_total": "Refit candidates that passed validation and were published (swap + checkpoint; label model)",
    "sbt_online_refits_rejected_total": "Refit candidates rejected by validation: scored worse than the incumbent (never published; label model)",
    "sbt_online_refits_skipped_total": "Refit triggers skipped for lack of buffered labeled rows (below min_refit_rows; label model)",
    "sbt_online_refit_errors_total": "Refits that died mid-flight and were absorbed by the trainer's supervision (label model)",
    "sbt_online_refit_seconds": "Wall-clock of one drain->refit->validate->publish cycle (histogram, label model)",
    "sbt_online_buffer_rows": "Labeled rows currently held by one online refit buffer (gauge; label model when attached)",
    "sbt_history_appends_total": "Records appended to the longitudinal history store (telemetry_dir()/history/history.jsonl)",
    "sbt_history_records": "Records seen by the latest history trend scan (gauge)",
    "sbt_history_groups": "Distinct (kind, key) groups in the latest history trend scan (gauge)",
    "sbt_history_digest_flips": "Digest/SLO flips found by the latest history trend scan (gauge; any nonzero is a regression finding)",
    "sbt_history_numeric_drift": "Numeric fields outside the CI-noise band in the latest history trend scan (gauge, advisory)",
    "sbt_program_cache_bytes": "Measured executable bytes resident in the unified program cache (gauge; unmeasured entries excluded, see sbt_capacity_unmeasured_entries)",
    "sbt_capacity_params_bytes": "Stacked-pytree parameter bytes held by one committed (model, version) (gauge, labels model+version)",
    "sbt_capacity_compiled_bytes": "Measured program-cache executable bytes attributed to one committed model (gauge, label model)",
    "sbt_capacity_resident_entries": "Program-cache entries attributed to one committed model (gauge, label model)",
    "sbt_capacity_unmeasured_entries": "Resident entries whose executable bytes could not be measured - flagged, never counted as 0 (gauge, label model)",
    "sbt_capacity_aot_disk_bytes": "AOT executable-cache bytes on disk for one committed model (gauge, label model)",
    "sbt_capacity_models": "Distinct models in the capacity ledger (gauge)",
    "sbt_capacity_demand_requests_total": "Requests served per model, fed from the packed-forward demand tap (label model)",
    "sbt_capacity_demand_rows_total": "Rows served per model, fed from the packed-forward demand tap (label model)",
    "sbt_capacity_demand_rate_rps": "Per-model request rate over the last classification window (gauge, label model)",
    "sbt_capacity_demand_rank": "Per-model popularity rank by cumulative requests, 1 = hottest (gauge, label model)",
    "sbt_capacity_demand_class": "Per-model demand class with hysteresis: 2 hot / 1 warm / 0 cold (gauge, label model)",
    "sbt_capacity_demand_dropped_total": "Demand observations dropped by the fixed-memory model cap (capacity plane max_models)",
    "sbt_capacity_cache_headroom_ratio": "Free-slot ratio of the program cache: (capacity - entries) / capacity (gauge)",
    "sbt_capacity_cold_resident_entries": "Program-cache entries owned by cold-demand-class models (gauge; the reclaim candidates)",
    "sbt_tenancy_tenants": "Tenants configured in the installed TenantFleet (gauge)",
    "sbt_tenancy_admitted_total": "Requests admitted by the tenancy admission controller (label tenant)",
    "sbt_tenancy_shed_total": "Requests shed by admission policy (labels tenant + reason: quota, priority, or quarantine)",
    "sbt_tenancy_overloads_total": "Downstream Overloaded sheds fed into the admission pressure window",
    "sbt_tenancy_pressure_level": "Admission pressure state: 0 normal / 1 shed batch class / 2 shed standard too (gauge)",
    "sbt_tenancy_demotions_total": "Tenants demoted from residency (programs released, AOT-persisted; label tenant)",
    "sbt_tenancy_restores_total": "Demoted tenants restored from their AOT cache on first hit (label tenant)",
    "sbt_tenancy_resident_tenants": "Tenants currently resident (compiled) under the residency budget (gauge)",
    "sbt_tenancy_pin_violations_total": "Evictions/demotions that had to sacrifice a hot-pinned entry (label tenant, or level=cache)",
    "sbt_tenancy_refit_denied_total": "Online-refit triggers denied by the per-tenant refit budget (label tenant)",
    "sbt_tenancy_latency_p99_ms": "Per-tenant served-request p99 latency in ms (gauge, label tenant; host-band, never digested)",
    "sbt_tenancy_latency_seconds": "Per-tenant served-request wall latency (log-scale histogram, label tenant, exemplar trace ids; bucket counts merge exactly across the fleet)",
    "sbt_tenancy_tail_p99_ms": "p99 latency in ms over the tail tenants - everyone but the Zipf head (gauge; the fleet SLO burn signal)",
    "sbt_tenant_quarantine_trips_total": "Tenants tripped into quarantine by the failure window (unlabeled total + label tenant)",
    "sbt_tenant_quarantine_shed_total": "Requests shed because their tenant is quarantined (unlabeled total + label tenant)",
    "sbt_tenant_quarantine_probes_total": "Single recovery probes admitted for quarantined tenants (label tenant)",
    "sbt_tenant_quarantine_recoveries_total": "Quarantined tenants recovered by a successful probe (label tenant)",
    "sbt_tenant_quarantine_failures_total": "Tenant-attributed failures fed into the quarantine window (labels tenant + kind)",
    "sbt_tenant_quarantine_active": "Tenants currently quarantined or probing (gauge)",
    "sbt_aot_load_corrupt_total": "Corrupt/truncated AOT cache reads degraded to a counted miss-plus-recompile (optional model label)",
    "sbt_serving_programs_released_total": "Compiled bucket executables dropped by executor release_programs (tenant demotion)",
    "sbt_online_refits_budget_denied_total": "Refit triggers dropped by the per-tenant refit budget hook (label model)",
    "sbt_process_device_bytes_in_use": "Device memory currently allocated, where the backend reports it (gauge, label device)",
    "sbt_process_device_bytes_limit": "Device memory capacity, where the backend reports it (gauge, label device)",
    "sbt_process_device_peak_bytes": "Peak device memory allocated since process start, where reported (gauge, label device)",
}


def _help_for(name: str) -> str | None:
    text = SERIES_HELP.get(name)
    if text is None and name.startswith("sbt_fit_"):
        key = name[len("sbt_fit_"):]
        text = f"fit_report_[{key!r}] exported as a gauge"
    return text

# The quantiles every histogram surfaces (snapshot/dump/varz/serving
# stats): median, tail, far tail — the serve-SLO trio.
QUANTILES: tuple[float, ...] = (0.5, 0.95, 0.99)


def _label_key(labels: dict[str, Any] | None) -> tuple:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonically increasing value (Prometheus ``counter``)."""

    kind = "counter"

    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError(f"counter increment must be >= 0, got {v}")
        self.value += v


class Gauge:
    """Last-write-wins value (Prometheus ``gauge``)."""

    kind = "gauge"

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, v: float = 1.0) -> None:
        self.value += v


class Histogram:
    """Log-scale bucketed distribution (Prometheus ``histogram``).

    Buckets store per-bucket counts; cumulative ``le`` counts are
    produced at render time (the exposition format's convention).
    ``observe(v, exemplar=...)`` additionally remembers the most
    recent exemplar (a trace id) per bucket, so a latency spike in the
    p99 bucket comes with a concrete request to go look up in the
    span log — the histogram-to-trace jump of OpenMetrics exemplars.

    Alongside newest-wins, a small **top-K-by-value reservoir**
    (``slow_exemplars``, :data:`RESERVOIR_K` entries) retains the
    LARGEST observations seen: newest-per-bucket alone would hand the
    tail explainer (``/debug/tail``) mostly fresh fast requests —
    under steady traffic the slow outlier that defined the p99 is
    evicted from its bucket within seconds. The rule is deterministic
    (a strictly greater value evicts the current minimum; ties keep
    the incumbent) and O(K) under the registry lock the observe
    already holds.
    """

    kind = "histogram"

    #: top-K-by-duration exemplar reservoir size (per histogram)
    RESERVOIR_K = 4

    def __init__(self, buckets: Iterable[float] = DEFAULT_BUCKETS) -> None:
        self.bounds = tuple(sorted(buckets))
        if not self.bounds or self.bounds[-1] != math.inf:
            self.bounds = self.bounds + (math.inf,)
        self.counts = [0] * len(self.bounds)
        self.sum = 0.0
        self.count = 0
        # bucket index -> {"trace_id", "value", "ts"} (last write wins:
        # the freshest example of that latency class is the useful one)
        self.exemplars: dict[int, dict[str, Any]] = {}
        # unordered top-K-by-value entries, same shape as exemplars
        self.slow_exemplars: list[dict[str, Any]] = []

    def observe(self, v: float, exemplar: str | None = None) -> None:
        v = float(v)
        for i, b in enumerate(self.bounds):
            if v <= b:
                self.counts[i] += 1
                if exemplar is not None:
                    entry = {
                        "trace_id": exemplar, "value": v,
                        "ts": time.time(),
                    }
                    self.exemplars[i] = entry
                    slow = self.slow_exemplars
                    if len(slow) < self.RESERVOIR_K:
                        slow.append(dict(entry))
                    else:
                        m = min(range(len(slow)),
                                key=lambda j: slow[j]["value"])
                        if v > slow[m]["value"]:
                            slow[m] = dict(entry)
                break
        # count AFTER the bucket: quantile() reads the live object
        # without the registry lock (stats paths), in the opposite
        # order — count first, then the counts copy — so a concurrent
        # reader can never see count > sum(counts)
        self.count += 1
        self.sum += v

    def quantile(self, q: float) -> float:
        """Estimate the ``q``-quantile by log-linear interpolation
        inside the bucket where the cumulative count crosses it.

        The grid is log-scale (decades by default), so interpolating
        in log space matches the distribution model the buckets
        already impose; the first bucket interpolates from one decade
        below its bound, and mass in the ``+Inf`` bucket clamps to the
        last finite bound (the estimate is a floor there — say so in
        dashboards). NaN when empty.
        """
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        # lock-free read off the live object (MicroBatcher.stats() and
        # /varz call this while the worker observes under the registry
        # lock): read count BEFORE copying counts — paired with
        # observe()'s bucket-before-count write order this guarantees
        # sum(counts) >= count, so the loop always crosses target
        count = self.count
        counts = list(self.counts)
        if count == 0:
            return math.nan
        target = q * count
        cum = 0
        for i, (b, c) in enumerate(zip(self.bounds, counts)):
            cum += c
            if cum >= target and c > 0:
                if not math.isfinite(b):
                    # beyond the grid: the last finite bound is all we
                    # can honestly claim
                    return self.bounds[i - 1] if i > 0 else math.inf
                lo = self.bounds[i - 1] if i > 0 else b / 10.0
                if lo <= 0:
                    lo = b / 10.0
                frac = (target - (cum - c)) / c
                return lo * (b / lo) ** frac
        return math.nan  # pragma: no cover — cum == count >= target

    def quantiles(self) -> dict[str, float | None]:
        """The standard trio (p50/p95/p99) as a JSON-friendly dict.
        Non-finite estimates (empty histogram) become None — `NaN` is
        not JSON, and these dicts land verbatim in /varz responses,
        flight dumps, and capture() metrics snapshots."""
        out: dict[str, float | None] = {}
        for q in QUANTILES:
            v = self.quantile(q)
            out[f"p{int(q * 100)}"] = v if math.isfinite(v) else None
        return out

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold ``other``'s observations into this histogram EXACTLY —
        bucket-wise count addition, so the merged histogram is
        indistinguishable from one that observed the concatenation of
        both observation streams (same bucket counts ⇒ same quantile
        estimates: the fleet aggregator's no-percentile-averaging
        guarantee rides on this). Requires identical bucket bounds —
        two grids cannot be combined without losing exactness, so a
        mismatch raises instead of approximating. Exemplars adopt the
        newer entry per bucket (last-write-wins, matching
        :meth:`observe`); the slow reservoirs merge by the reservoir's
        own rule — the K largest values across both peers win (ties
        broken toward the newer ``ts``), so the fleet view's tail
        exemplars are exactly the fleet's slowest requests. Returns
        ``self``."""
        if self.bounds != other.bounds:
            raise ValueError(
                f"cannot merge histograms with different bounds "
                f"({len(self.bounds)} vs {len(other.bounds)} buckets)"
            )
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        for i, ex in other.exemplars.items():
            mine = self.exemplars.get(i)
            if mine is None or ex.get("ts", 0) >= mine.get("ts", 0):
                self.exemplars[i] = dict(ex)
        pool = self.slow_exemplars + [dict(e) for e in
                                      other.slow_exemplars]
        pool.sort(key=lambda e: (-e.get("value", 0.0),
                                 -e.get("ts", 0.0)))
        self.slow_exemplars = pool[:self.RESERVOIR_K]
        return self


# sbt-lint: shared-state
class Registry:
    """Thread-safe metric store keyed by ``(name, labels)``."""

    def __init__(self) -> None:
        self._lock = make_lock("telemetry.registry")
        self._metrics: dict[tuple[str, tuple], Any] = {}

    def _get_locked(self, name: str, labels, cls):
        """Fetch-or-create under the ALREADY-HELD lock."""
        key = (name, _label_key(labels))
        m = self._metrics.get(key)
        if m is None:
            # sbt-lint: disable=shared-state-unlocked — every caller holds self._lock (enforced by the _locked naming convention)
            m = self._metrics[key] = cls()
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}"
            )
        return m

    def peek(self, name: str, labels: dict | None = None):
        """The live metric object for ``(name, labels)``, or None —
        a read that never CREATES the series. The alert engine samples
        series it does not own; materializing them at 0.0 would make
        "absent" and "zero" indistinguishable (an ``op "<"`` rule
        would page on data that was never written)."""
        with self._lock:
            return self._metrics.get((name, _label_key(labels)))

    def counter(self, name: str, labels: dict | None = None) -> Counter:
        with self._lock:
            return self._get_locked(name, labels, Counter)

    def gauge(self, name: str, labels: dict | None = None) -> Gauge:
        with self._lock:
            return self._get_locked(name, labels, Gauge)

    def histogram(self, name: str, labels: dict | None = None) -> Histogram:
        with self._lock:
            return self._get_locked(name, labels, Histogram)

    # convenience mutators (one lock round-trip each; call sites stay
    # one-liners behind the enabled() gate)

    def inc(self, name: str, v: float = 1.0, labels: dict | None = None) -> None:
        with self._lock:
            self._get_locked(name, labels, Counter).inc(v)

    def inc_many(self, items: Iterable[tuple[str, float]]) -> None:
        """Increment several (unlabeled) counters under ONE lock
        round-trip — the serving hot path counts 4+ series per
        forward, and per-call lock acquisitions were measurable
        there."""
        with self._lock:
            for name, v in items:
                self._get_locked(name, None, Counter).inc(v)

    def set(self, name: str, v: float, labels: dict | None = None) -> None:
        with self._lock:
            self._get_locked(name, labels, Gauge).set(v)

    def observe(self, name: str, v: float, labels: dict | None = None,
                exemplar: str | None = None) -> None:
        with self._lock:
            self._get_locked(name, labels, Histogram).observe(
                v, exemplar=exemplar
            )

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()

    def snapshot(self, *, quantiles: bool = False) -> list[dict]:
        """JSON-serializable dump of every metric (the ``metrics``
        JSONL event body, and the input to :func:`render_prometheus`).

        ``quantiles=True`` adds interpolated p50/p95/p99 to each
        histogram entry; the default skips that work because the two
        hottest callers — the ``/metrics`` scrape and the JSONL
        metrics flush — never read them (consumers of a bare snapshot
        can always reconstruct via :func:`snapshot_quantiles`, the
        bucket counts are in the entry)."""
        out = []
        with self._lock:
            for (name, labels), m in sorted(self._metrics.items()):
                if m.kind == "histogram":
                    entry = histogram_entry(name, dict(labels), m)
                else:
                    entry = {
                        "name": name,
                        "kind": m.kind,
                        "labels": dict(labels),
                        "value": m.value,
                    }
                out.append(entry)
        # quantile interpolation happens OUTSIDE the lock, from each
        # entry's copied bucket counts — every metric writer blocks on
        # this lock
        if quantiles:
            for entry in out:
                if entry["kind"] == "histogram":
                    entry["quantiles"] = snapshot_quantiles(entry)
        return out


def _escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, and
    newline must be escaped or the sample line is unparseable (a model
    name like ``c:\\models`` or ``he said "v2"`` would tear the whole
    scrape otherwise). Order matters: backslash first."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    """HELP text escaping per the exposition format: backslash and
    newline only (quotes are legal there)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: dict, extra: dict | None = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    body = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(merged.items())
    )
    return "{" + body + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    # non-finite first: int(NaN)/int(inf) raise, and a diverged fit's
    # loss_mean=NaN must not take the instrument panel down with it
    # (Prometheus text spec spells these NaN/+Inf/-Inf)
    if not math.isfinite(f):
        return "NaN" if math.isnan(f) else ("+Inf" if f > 0 else "-Inf")
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def histogram_entry(name: str, labels: dict, h: Histogram) -> dict:
    """Serialize one histogram as a snapshot entry — the JSON shape
    :meth:`Registry.snapshot` emits and :func:`histogram_from_entry`
    inverts. One serializer for both the live registry and the fleet
    merge (a shape drift between them would silently break the
    ``dump --merge`` / ``/fleet/varz`` round-trip)."""
    entry: dict[str, Any] = {
        "name": name,
        "kind": "histogram",
        "labels": dict(labels),
        "buckets": [
            ["+Inf" if b == math.inf else b, c]
            for b, c in zip(h.bounds, h.counts)
        ],
        "sum": h.sum,
        "count": h.count,
    }
    if h.exemplars:
        entry["exemplars"] = [
            {
                "le": "+Inf" if h.bounds[i] == math.inf else h.bounds[i],
                **ex,
            }
            for i, ex in sorted(h.exemplars.items())
        ]
    if h.slow_exemplars:
        entry["slow_exemplars"] = sorted(
            (dict(ex) for ex in h.slow_exemplars),
            key=lambda e: (-e.get("value", 0.0), -e.get("ts", 0.0)),
        )
    return entry


def histogram_from_entry(entry: dict) -> Histogram:
    """Reconstruct a live :class:`Histogram` from one snapshot entry
    (the JSON shape :meth:`Registry.snapshot` emits). The exemplar list
    is folded back keyed by bucket index so round-tripped histograms
    merge like live ones."""
    h = Histogram(buckets=[
        math.inf if b == "+Inf" else float(b)
        for b, _ in entry["buckets"]
    ])
    h.counts = [int(c) for _, c in entry["buckets"]]
    h.count = int(entry["count"])
    h.sum = float(entry["sum"])
    bound_index = {b: i for i, b in enumerate(h.bounds)}
    for ex in entry.get("exemplars", ()):
        le = ex.get("le")
        i = bound_index.get(math.inf if le == "+Inf" else float(le))
        if i is not None:
            h.exemplars[i] = {k: v for k, v in ex.items() if k != "le"}
    h.slow_exemplars = [dict(ex) for ex in
                        entry.get("slow_exemplars", ())]
    return h


def snapshot_quantiles(entry: dict) -> dict[str, float]:
    """p50/p95/p99 for one histogram snapshot entry. Live snapshots
    carry them precomputed; entries read back from an old JSONL log
    are reconstructed from their bucket counts (same interpolation)."""
    if "quantiles" in entry:
        return entry["quantiles"]
    return histogram_from_entry(entry).quantiles()


def render_prometheus(snapshot: list[dict]) -> str:
    """Prometheus text exposition of a :meth:`Registry.snapshot`.

    Series with an entry in :data:`SERIES_HELP` (or an ``sbt_fit_*``
    name) get a ``# HELP`` line ahead of their ``# TYPE``, once per
    metric name. Label values are escaped per the format spec.
    """
    lines: list[str] = []
    seen_type: set[str] = set()
    for entry in snapshot:
        name, kind, labels = entry["name"], entry["kind"], entry["labels"]
        if name not in seen_type:
            help_text = _help_for(name)
            if help_text is not None:
                lines.append(f"# HELP {name} {_escape_help(help_text)}")
            lines.append(f"# TYPE {name} {kind}")
            seen_type.add(name)
        if kind == "histogram":
            cum = 0
            for le, c in entry["buckets"]:
                cum += c
                lines.append(
                    f"{name}_bucket"
                    f"{_fmt_labels(labels, {'le': le})} {cum}"
                )
            lines.append(
                f"{name}_sum{_fmt_labels(labels)} "
                f"{_fmt_value(entry['sum'])}"
            )
            lines.append(
                f"{name}_count{_fmt_labels(labels)} {entry['count']}"
            )
        else:
            lines.append(
                f"{name}{_fmt_labels(labels)} {_fmt_value(entry['value'])}"
            )
    return "\n".join(lines) + ("\n" if lines else "")
