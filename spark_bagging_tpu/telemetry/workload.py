"""Workload capture: turn live serving traffic into a replayable file.

PR 5 made every served request traceable; this module makes the
request STREAM itself a first-class artifact. A
:class:`WorkloadRecorder` subscribes to the process event stream
(the same sink seam the JSONL capture and the flight recorder use) and
records one entry per ``serving_request`` arrival event emitted by
``MicroBatcher.submit()``: relative arrival time, row count,
dtype/width, the shape bucket the rows map to, and a concurrency
epoch. The result serializes as a versioned ``*.workload.jsonl`` that
``benchmarks/replay.py`` can replay deterministically against a real
serving stack — overload behavior, tail latency, and padding waste
become regression tests instead of incidents, and the recorded stream
is the input the online bootstrap trainer (ROADMAP item 2) will fit
from.

File format (``WORKLOAD_SCHEMA_VERSION``): line 1 is a header object
(``kind="workload_header"``, schema version, source, generator/seed
for synthetic workloads, request count, duration, feature width);
every following line is one request::

    {"t": 0.0135, "rows": 2, "width": 32, "dtype": "float32",
     "bucket": 8, "epoch": 0}

- ``t`` — arrival time in seconds relative to the first request
  (monotonic clock at capture; the replayer's virtual clock).
- ``bucket`` — the executor ladder rung ``rows`` maps to at capture
  time (padding-waste attribution without re-deriving ladder bounds);
  ``null`` when the serving stack had no bucket ladder.
- ``epoch`` — concurrency epoch: increments whenever the gap since
  the previous arrival exceeds ``epoch_gap_s`` (default 1 s). Distinct
  epochs are distinct traffic waves — the replayer and the online
  trainer can treat them as independent load regimes.

When no capture exists, :func:`synthetic_workload` generates one from
a seeded arrival model (``poisson`` / ``bursty`` / ``diurnal``) — same
seed, same workload, byte-for-byte identical entries.
"""

from __future__ import annotations

import json
import math
import os
import time
from collections import deque
from typing import Any, Iterable

from spark_bagging_tpu.analysis.locks import make_lock

WORKLOAD_SCHEMA_VERSION = 1

#: Default gap (seconds) between arrivals that starts a new
#: concurrency epoch.
DEFAULT_EPOCH_GAP_S = 1.0


class WorkloadRequest:
    """One recorded (or generated) request arrival."""

    __slots__ = ("t", "rows", "width", "dtype", "bucket", "epoch")

    def __init__(self, t: float, rows: int, width: int | None,
                 dtype: str = "float32", bucket: int | None = None,
                 epoch: int = 0) -> None:
        self.t = float(t)
        self.rows = int(rows)
        self.width = None if width is None else int(width)
        self.dtype = str(dtype)
        self.bucket = None if bucket is None else int(bucket)
        self.epoch = int(epoch)

    def to_dict(self) -> dict[str, Any]:
        return {
            "t": self.t, "rows": self.rows, "width": self.width,
            "dtype": self.dtype, "bucket": self.bucket,
            "epoch": self.epoch,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "WorkloadRequest":
        return cls(
            t=d["t"], rows=d["rows"], width=d.get("width"),
            dtype=d.get("dtype", "float32"), bucket=d.get("bucket"),
            epoch=d.get("epoch", 0),
        )

    def __repr__(self) -> str:
        return (f"WorkloadRequest(t={self.t:.4f}, rows={self.rows}, "
                f"epoch={self.epoch})")


class Workload:
    """An ordered request stream plus its provenance header."""

    def __init__(
        self,
        requests: Iterable[WorkloadRequest],
        *,
        source: str = "capture",
        generator: str | None = None,
        seed: int | None = None,
        created_ts: float | None = None,
    ) -> None:
        self.requests = sorted(requests, key=lambda r: r.t)
        self.source = source
        self.generator = generator
        self.seed = seed
        self.created_ts = created_ts

    # -- derived facts -------------------------------------------------

    @property
    def n_requests(self) -> int:
        return len(self.requests)

    @property
    def duration_s(self) -> float:
        return self.requests[-1].t if self.requests else 0.0

    @property
    def total_rows(self) -> int:
        return sum(r.rows for r in self.requests)

    def summary(self) -> dict[str, Any]:
        """JSON-friendly digest (``/debug/workload``, replay reports)."""
        rows = [r.rows for r in self.requests]
        dur = self.duration_s
        return {
            "schema": WORKLOAD_SCHEMA_VERSION,
            "source": self.source,
            "generator": self.generator,
            "seed": self.seed,
            "n_requests": self.n_requests,
            "duration_s": round(dur, 6),
            "total_rows": self.total_rows,
            "mean_rps": (round(self.n_requests / dur, 2) if dur > 0
                         else None),
            "rows_min": min(rows) if rows else None,
            "rows_max": max(rows) if rows else None,
            "n_epochs": (self.requests[-1].epoch + 1 if self.requests
                         else 0),
        }

    # -- (de)serialization ---------------------------------------------

    def header(self) -> dict[str, Any]:
        return {
            "kind": "workload_header",
            "schema": WORKLOAD_SCHEMA_VERSION,
            "source": self.source,
            "generator": self.generator,
            "seed": self.seed,
            "n_requests": self.n_requests,
            "duration_s": self.duration_s,
            "width": (self.requests[0].width if self.requests else None),
            "created_ts": self.created_ts,
        }

    def save(self, path: str) -> str:
        """Write the versioned ``*.workload.jsonl`` (header line first,
        then one line per request, arrival order). Returns ``path``."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.header(), f)
            f.write("\n")
            for r in self.requests:
                json.dump(r.to_dict(), f)
                f.write("\n")
        os.replace(tmp, path)  # a replayer never sees a torn file
        return path


def load_workload(path: str) -> Workload:
    """Parse a ``*.workload.jsonl`` back into a :class:`Workload`.

    Loud on malformed input: a replay against a torn or
    wrong-schema-version file must fail before it produces numbers
    someone gates a deploy on.
    """
    with open(path) as f:
        first = f.readline().strip()
        if not first:
            raise ValueError(f"{path}: empty workload file")
        header = json.loads(first)
        if header.get("kind") != "workload_header":
            raise ValueError(
                f"{path}: first line is not a workload_header "
                f"(got kind={header.get('kind')!r})"
            )
        schema = header.get("schema")
        if schema != WORKLOAD_SCHEMA_VERSION:
            raise ValueError(
                f"{path}: workload schema {schema!r} not supported "
                f"(this build reads {WORKLOAD_SCHEMA_VERSION})"
            )
        requests = []
        for line in f:
            line = line.strip()
            if line:
                requests.append(WorkloadRequest.from_dict(json.loads(line)))
    wl = Workload(
        requests,
        source=header.get("source", "capture"),
        generator=header.get("generator"),
        seed=header.get("seed"),
        created_ts=header.get("created_ts"),
    )
    declared = header.get("n_requests")
    if declared is not None and declared != wl.n_requests:
        raise ValueError(
            f"{path}: header declares {declared} requests but the file "
            f"holds {wl.n_requests} — truncated capture?"
        )
    return wl


def assign_epochs(requests: list[WorkloadRequest],
                  gap_s: float = DEFAULT_EPOCH_GAP_S) -> None:
    """Assign concurrency epochs in place: a gap larger than ``gap_s``
    between consecutive arrivals starts a new epoch (a new traffic
    wave)."""
    epoch = 0
    prev_t: float | None = None
    for r in requests:
        if prev_t is not None and r.t - prev_t > gap_s:
            epoch += 1
        r.epoch = epoch
        prev_t = r.t


# -- the live recorder --------------------------------------------------

# sbt-lint: shared-state
class WorkloadRecorder:
    """Subscribe to the event stream and capture the request arrivals.

    Implements the sink protocol (``emit(event)``) like the flight
    recorder; only ``serving_request`` events (emitted by
    ``MicroBatcher.submit`` whenever an arrival consumer is active —
    :func:`capture_active` is the gate the batcher checks) are
    recorded — spans, metrics flushes, and fault events pass through
    untouched. ``capacity`` bounds memory (oldest entries drop with a
    one-time truncation mark in :meth:`summary`); arrival times are
    re-based to the first recorded event.
    """

    def __init__(self, *, capacity: int = 1_000_000,
                 epoch_gap_s: float = DEFAULT_EPOCH_GAP_S) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.epoch_gap_s = float(epoch_gap_s)
        self._lock = make_lock("telemetry.workload")
        # a deque ring, not a list: eviction at capacity must stay
        # O(1) per arrival — this sink sits on the submit path of a
        # LIVE serving process, and a recorder pinned at capacity
        # would otherwise pay O(capacity) per request
        self._entries: deque[WorkloadRequest] = deque(maxlen=self.capacity)
        self._t0: float | None = None
        self._prev_t: float | None = None
        self._epoch = 0
        self._dropped = 0
        # running aggregates over EVERYTHING seen (evicted entries
        # included): summary() reads these instead of copying the ring
        # — it shares this lock with emit() on the live submit path,
        # so a /debug/workload scrape must stay O(1), not O(capacity)
        self._n_seen = 0
        self._total_rows = 0
        self._rows_min: int | None = None
        self._rows_max: int | None = None
        self._recording = False
        self.t_started: float | None = None

    # -- sink protocol -------------------------------------------------

    def emit(self, event: dict) -> None:
        if event.get("kind") != "serving_request":
            return
        t_mono = event.get("t_mono")
        if t_mono is None:  # a hand-rolled event without the clock stamp
            t_mono = time.monotonic()
        with self._lock:
            if self._t0 is None:
                self._t0 = t_mono
            t = t_mono - self._t0
            if self._prev_t is not None and t - self._prev_t > self.epoch_gap_s:
                self._epoch += 1
            self._prev_t = t
            if len(self._entries) == self.capacity:
                self._dropped += 1  # the append below evicts the oldest
            rows = int(event.get("rows", 1))
            self._n_seen += 1
            self._total_rows += rows
            if self._rows_min is None or rows < self._rows_min:
                self._rows_min = rows
            if self._rows_max is None or rows > self._rows_max:
                self._rows_max = rows
            self._entries.append(WorkloadRequest(
                t=t,
                rows=rows,
                width=event.get("width"),
                dtype=str(event.get("dtype", "float32")),
                bucket=event.get("bucket"),
                epoch=self._epoch,
            ))

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "WorkloadRecorder":
        """Begin a capture session (idempotent while recording).

        A start after a :meth:`stop` is a NEW session, never a resume:
        the previous session's data was already handed out by stop()
        (and stays readable via :meth:`workload` until this call), so
        the entries, t=0 anchor, epoch counter, and aggregates all
        reset — otherwise the second session's arrivals would carry
        the whole inter-session wall gap as schedule time. Recording
        requires telemetry to be enabled — arrival events are only
        emitted behind the ``telemetry.enabled()`` gate."""
        global _n_recording
        from spark_bagging_tpu.telemetry.state import STATE

        if not STATE.enabled:
            import warnings

            # subscribe anyway (telemetry may be re-enabled mid-
            # session), but a capture opened while the arrival events
            # it depends on are switched off deserves a loud heads-up
            # — the alternative is an operator discovering an empty
            # workload file after the incident they meant to record
            warnings.warn(
                "workload recording started while telemetry is "
                "disabled: serving arrival events are not emitted, so "
                "this capture will stay EMPTY until telemetry.enable()",
                RuntimeWarning,
                stacklevel=2,
            )
        with self._lock:
            already = self._recording
            if not already:
                self._entries.clear()
                self._t0 = None
                self._prev_t = None
                self._epoch = 0
                self._dropped = 0
                self._n_seen = 0
                self._total_rows = 0
                self._rows_min = None
                self._rows_max = None
                self._recording = True
                self.t_started = time.time()
        if not already:
            with _interest_lock:
                _n_recording += 1
                _recording_instances.append(self)
            STATE.add_sink(self)
        return self

    def stop(self) -> Workload:
        """Detach and return the captured :class:`Workload`."""
        global _n_recording
        from spark_bagging_tpu.telemetry.state import STATE

        with self._lock:
            was = self._recording
            self._recording = False
        if was:
            with _interest_lock:
                _n_recording -= 1
                if self in _recording_instances:
                    _recording_instances.remove(self)
            STATE.remove_sink(self)
        return self.workload()

    @property
    def recording(self) -> bool:
        return self._recording

    # -- introspection -------------------------------------------------

    def workload(self) -> Workload:
        with self._lock:
            entries = list(self._entries)
        return Workload(entries, source="capture",
                        created_ts=self.t_started)

    def drain(self, max_requests: int | None = None) -> list[WorkloadRequest]:
        """Consume the captured window: return up to ``max_requests``
        of the MOST RECENT recorded arrivals and remove everything
        returned from the ring (recording continues; the running
        aggregates keep covering the whole seen stream). This is the
        online trainer's hand-off seam — each drift-triggered refit
        drains the traffic window that tripped the alert, and the next
        refit starts from an empty window instead of re-consuming the
        same incident. Returned entries are arrival records (schedule
        + shapes), the refit transcript's bookkeeping; payloads and
        labels ride the trainer's :class:`~spark_bagging_tpu.online
        .trainer.LabeledBuffer`, which the serving edge feeds."""
        import itertools

        with self._lock:
            entries = list(self._entries)
            if max_requests is not None and max_requests >= 0:
                entries = entries[-max_requests:] if max_requests else []
            if entries:
                # islice, never per-index deque access: this lock sits
                # on the live submit path, and rebuilding the kept
                # prefix by indexing would be O(keep²) inside it
                keep = len(self._entries) - len(entries)
                kept = list(itertools.islice(self._entries, keep))
                self._entries.clear()
                self._entries.extend(kept)
        return entries

    def summary(self) -> dict[str, Any]:
        """Digest for ``/debug/workload``: the captured stream so far,
        plus recorder state. Built from running aggregates — O(1)
        under the lock emit() shares, so scraping it mid-traffic never
        stalls concurrent ``submit()`` calls (aggregates cover the
        whole SEEN stream; ``n_requests`` is the ring, ``dropped`` the
        evicted difference)."""
        with self._lock:
            dur = self._prev_t or 0.0
            return {
                "schema": WORKLOAD_SCHEMA_VERSION,
                "source": "capture",
                "generator": None,
                "seed": None,
                "n_requests": len(self._entries),
                "n_seen": self._n_seen,
                "duration_s": round(dur, 6),
                "total_rows": self._total_rows,
                "mean_rps": (round(self._n_seen / dur, 2) if dur > 0
                             else None),
                "rows_min": self._rows_min,
                "rows_max": self._rows_max,
                "n_epochs": self._epoch + 1 if self._n_seen else 0,
                "recording": self._recording,
                "capacity": self.capacity,
                "dropped": self._dropped,
                "t_started": self.t_started,
            }

    def save(self, path: str) -> str:
        return self.workload().save(path)


# every RECORDING WorkloadRecorder instance (default or direct), in
# start order: the batcher's submit path gates arrival-event
# construction on the count (via telemetry.arrival_events_wanted),
# and /debug/workload resolves its live view from it — a directly-
# constructed recorder (the documented alternative to the default)
# must be just as visible as the default one
_interest_lock = make_lock("telemetry.workload.interest")
_n_recording = 0
_recording_instances: list["WorkloadRecorder"] = []


def capture_active() -> bool:
    """True while ANY workload recorder is recording (a bare int read
    — this sits on the serving submit path)."""
    return _n_recording > 0


_default: WorkloadRecorder | None = None
# concurrent first record() calls must not each subscribe a recorder —
# the loser would be an undetachable sink double-counting arrivals
# (same hazard the flight recorder's default lock guards against)
_default_lock = make_lock("telemetry.workload.default")


def record(**kwargs: Any) -> WorkloadRecorder:
    """Start the process-default recorder: returns the live one if a
    capture session is running, else creates a FRESH recorder. A
    stopped default — whether via module-level :func:`stop` or the
    instance's own ``stop()`` — is a finished session, never resumed:
    its entries, t=0 anchor, and epoch counter must not bleed into
    the next capture. ``kwargs`` are :class:`WorkloadRecorder` options
    and apply whenever a fresh recorder is created; passing them while
    a session is LIVE warns instead of silently dropping them."""
    global _default
    with _default_lock:
        if _default is None or not _default.recording:
            _default = WorkloadRecorder(**kwargs)
        elif kwargs:
            import warnings

            warnings.warn(
                "a workload recording session is live; record() "
                f"options {sorted(kwargs)} are ignored (stop() the "
                "default first, or construct WorkloadRecorder "
                "directly)",
                RuntimeWarning,
                stacklevel=2,
            )
        rec = _default
        # start INSIDE the lock: a concurrent record() racing this one
        # must see recording=True, not conclude "stopped session" and
        # replace a recorder whose sink subscription is in flight
        rec.start()
    return rec


def stop() -> Workload | None:
    """Stop AND retire the process-default recorder; returns its
    workload (or None when none was ever started). Retiring matters:
    a capture session ends here, so the next :func:`record` starts a
    FRESH recorder — entries, the t=0 anchor, and the epoch counter
    from the previous session must not bleed into it."""
    global _default
    with _default_lock:
        rec = _default
        _default = None
    if rec is None:
        return None
    return rec.stop()


def active() -> WorkloadRecorder | None:
    """A recorder that is currently recording, or None (what
    ``/debug/workload`` serves): the process default when its session
    is live, else the most recently started recording instance — a
    directly-constructed ``WorkloadRecorder().start()`` (the
    documented alternative when the default is busy) is just as
    visible to the live view as the default one."""
    rec = _default
    if rec is not None and rec.recording:
        return rec
    with _interest_lock:
        return _recording_instances[-1] if _recording_instances else None


# -- synthetic workloads ------------------------------------------------

def _draw_rows(rng, rows) -> int:
    if isinstance(rows, int):
        return rows
    seq = list(rows)
    return int(seq[int(rng.integers(0, len(seq)))])


def synthetic_workload(
    kind: str = "poisson",
    *,
    rate_rps: float = 200.0,
    duration_s: float = 1.0,
    seed: int = 0,
    rows: int | tuple[int, ...] = 1,
    width: int = 16,
    bucket_bounds: tuple[int, int] | None = None,
    burst_every_s: float = 0.25,
    burst_size: int = 32,
    diurnal_period_s: float | None = None,
    diurnal_depth: float = 0.8,
    epoch_gap_s: float = DEFAULT_EPOCH_GAP_S,
) -> Workload:
    """Generate a seeded arrival schedule when no capture exists.

    ``kind``:

    - ``"poisson"`` — homogeneous Poisson arrivals at ``rate_rps``
      (exponential inter-arrival gaps): steady open-loop traffic.
    - ``"bursty"`` — the Poisson base plus a burst of ``burst_size``
      near-simultaneous requests every ``burst_every_s``: the overload
      / backpressure scenario.
    - ``"diurnal"`` — inhomogeneous Poisson whose rate swings
      sinusoidally (``rate_rps * (1 + diurnal_depth * sin)``, period
      ``diurnal_period_s`` defaulting to the full duration): the
      slow-tide load shape, generated by thinning.

    ``rows`` is a fixed per-request row count or a tuple of choices
    (uniform). Deterministic: same arguments + same seed produce
    byte-identical workloads (``numpy.random.default_rng(seed)`` is
    the only randomness source — no wall clock anywhere).
    """
    import numpy as np

    if rate_rps <= 0 or duration_s <= 0:
        raise ValueError(
            f"need rate_rps > 0 and duration_s > 0, got "
            f"{rate_rps}, {duration_s}"
        )
    rng = np.random.default_rng(seed)
    times: list[float] = []
    if kind == "poisson":
        t = 0.0
        while True:
            t += float(rng.exponential(1.0 / rate_rps))
            if t > duration_s:
                break
            times.append(t)
    elif kind == "bursty":
        t = 0.0
        while True:
            t += float(rng.exponential(1.0 / rate_rps))
            if t > duration_s:
                break
            times.append(t)
        n_bursts = int(duration_s / burst_every_s)
        for b in range(1, n_bursts + 1):
            t_b = b * burst_every_s
            if t_b > duration_s:
                break
            # a burst is near-simultaneous, not exactly simultaneous:
            # spread over ~1 ms so arrival order stays well-defined
            offs = np.sort(rng.uniform(0.0, 1e-3, size=burst_size))
            times.extend(float(t_b + o) for o in offs)
    elif kind == "diurnal":
        period = diurnal_period_s or duration_s
        if not 0.0 <= diurnal_depth <= 1.0:
            raise ValueError(
                f"diurnal_depth must be in [0, 1], got {diurnal_depth}"
            )
        # thinning: draw from the peak rate, keep with p = rate(t)/peak
        peak = rate_rps * (1.0 + diurnal_depth)
        t = 0.0
        while True:
            t += float(rng.exponential(1.0 / peak))
            if t > duration_s:
                break
            rate_t = rate_rps * (
                1.0 + diurnal_depth * math.sin(2.0 * math.pi * t / period)
            )
            if float(rng.uniform()) < rate_t / peak:
                times.append(t)
    else:
        raise ValueError(
            f"unknown workload kind {kind!r}; "
            "have poisson, bursty, diurnal"
        )

    times.sort()
    requests = []
    for t in times:
        n = _draw_rows(rng, rows)
        bucket = None
        if bucket_bounds is not None:
            from spark_bagging_tpu.serving.buckets import bucket_for

            bucket = bucket_for(n, *bucket_bounds)
        requests.append(WorkloadRequest(
            t=t, rows=n, width=width, dtype="float32", bucket=bucket,
        ))
    assign_epochs(requests, epoch_gap_s)
    return Workload(requests, source="synthetic", generator=kind,
                    seed=seed)
