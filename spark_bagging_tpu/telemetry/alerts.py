"""Declarative alert engine over the live metrics registry.

Drift gauges (``sbt_quality_*``), serving counters (``sbt_serving_*``)
and every other registry series become *actionable* here: an
:class:`AlertRule` names a series, a threshold, and a multi-window
burn-rate pair, and the :class:`AlertEngine` turns breaches into
``alert_fired`` / ``alert_resolved`` events — which the flight
recorder treats as triggers, so an alert arrives with the black box of
what was happening when it fired.

**Rule grammar** (``AlertRule.from_dict``; JSON-friendly)::

    {"name":        "feature-drift",
     "series":      "sbt_quality_psi_max",     # registry series name
     "labels":      null,                      # optional label match
     "kind":        "value",                   # "value" (gauge) |
                                               # "rate" (counter /s)
     "op":          ">",                       # ">" | "<"
     "threshold":   0.5,
     "fast_window_s": 30.0,                    # both windows must
     "slow_window_s": 300.0,                   # breach to fire
     "cooldown_s":  300.0,                     # min gap between fires
     "severity":    "page",
     "description": "live traffic no longer matches training"}

**Multi-window burn rate** (the SRE-workbook shape): the condition
must hold over BOTH the fast and the slow window — the fast window
catches the incident quickly, the slow window keeps a transient blip
from paging. ``kind="rate"`` evaluates a counter's per-second rate
over each window; ``kind="value"`` requires every sample in the
window to breach. Either way a window only counts once the engine has
watched at least that long (no alert from one lucky sample at
startup).

**Evaluation is pull-based and clock-injectable**: nothing runs per
request — call :meth:`AlertEngine.evaluate` from a scrape (the
``/alerts`` endpoint does), a loop, or a replay harness. ``now`` is
injectable, which is how ``benchmarks/replay.py --drift`` drives the
engine on its virtual clock and gets byte-identical alert behavior
run after run.

**Lifecycle**: fire emits one ``alert_fired`` event (flight-recorder
trigger), bumps ``sbt_alerts_fired_total{rule=...}``, and marks the
rule active; while active it cannot re-fire (one incident, one
alert). It resolves — ``alert_resolved``, counted — when the latest
sample stops breaching, and a re-fire within ``cooldown_s`` of the
last fire is suppressed (counted in
``sbt_alerts_suppressed_total``), so a flapping series cannot page
once per flap.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any

from spark_bagging_tpu.analysis.locks import make_lock
from spark_bagging_tpu.telemetry.state import STATE


def _emit(event: dict) -> None:
    """Deliver an event to the process sinks (the facade's emit_event
    without the facade import — this module is imported BY it)."""
    if STATE.enabled and STATE._sinks:
        event.setdefault("ts", time.time())
        STATE.emit(event)


class AlertRule:
    """One declarative condition over a registry series (see module
    docstring for the grammar)."""

    KINDS = ("value", "rate")
    OPS = (">", "<")
    FIELDS = (
        "name", "series", "labels", "kind", "op", "threshold",
        "fast_window_s", "slow_window_s", "cooldown_s", "severity",
        "description",
    )

    def __init__(
        self,
        name: str,
        series: str,
        *,
        threshold: float,
        labels: dict[str, Any] | None = None,
        kind: str = "value",
        op: str = ">",
        fast_window_s: float = 30.0,
        slow_window_s: float = 300.0,
        cooldown_s: float = 300.0,
        severity: str = "page",
        description: str = "",
    ) -> None:
        if kind not in self.KINDS:
            raise ValueError(
                f"rule {name!r}: kind must be one of {self.KINDS}, "
                f"got {kind!r}"
            )
        if op not in self.OPS:
            raise ValueError(
                f"rule {name!r}: op must be one of {self.OPS}, got {op!r}"
            )
        if not (0 < fast_window_s <= slow_window_s):
            raise ValueError(
                f"rule {name!r}: need 0 < fast_window_s <= "
                f"slow_window_s, got {fast_window_s}, {slow_window_s}"
            )
        if cooldown_s < 0:
            raise ValueError(
                f"rule {name!r}: cooldown_s must be >= 0, got "
                f"{cooldown_s}"
            )
        self.name = str(name)
        self.series = str(series)
        self.labels = dict(labels) if labels else None
        self.kind = kind
        self.op = op
        self.threshold = float(threshold)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.cooldown_s = float(cooldown_s)
        self.severity = str(severity)
        self.description = str(description)

    def breaches(self, v: float) -> bool:
        return v > self.threshold if self.op == ">" else v < self.threshold

    def to_dict(self) -> dict[str, Any]:
        return {f: getattr(self, f) for f in self.FIELDS}

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "AlertRule":
        unknown = set(d) - set(cls.FIELDS)
        if unknown:
            raise ValueError(
                f"unknown alert rule fields {sorted(unknown)}; have "
                f"{list(cls.FIELDS)}"
            )
        if "name" not in d or "series" not in d or "threshold" not in d:
            raise ValueError(
                "an alert rule needs at least name, series, threshold"
            )
        kw = dict(d)
        name = kw.pop("name")
        series = kw.pop("series")
        return cls(name, series, **kw)

    def __repr__(self) -> str:
        return (f"AlertRule({self.name!r}, {self.series!r} {self.op} "
                f"{self.threshold}, windows=({self.fast_window_s}, "
                f"{self.slow_window_s})s)")


class _RuleState:
    __slots__ = ("rule", "samples", "t_first", "active", "last_fired",
                 "fired", "resolved", "suppressed", "last_value",
                 "last_eval")

    def __init__(self, rule: AlertRule) -> None:
        self.rule = rule
        # (t, value) samples; pruned to the slow window plus one older
        # sample (the rate anchor / coverage witness)
        self.samples: deque[tuple[float, float]] = deque()
        self.t_first: float | None = None
        self.active = False
        self.last_fired: float | None = None
        self.fired = 0
        self.resolved = 0
        self.suppressed = 0
        self.last_value: float | None = None
        self.last_eval: float | None = None


# sbt-lint: shared-state
class AlertEngine:
    """Evaluate a rule set against the live registry; emit events.

    Construct with rules (or :meth:`add_rule` later) and call
    :meth:`evaluate` on whatever cadence suits — scrape handlers,
    a periodic loop, or a replay's virtual clock via ``now=``. The
    engine holds no thread of its own: deterministic by construction.
    """

    def __init__(self, rules=(), *, registry=None) -> None:
        self._lock = make_lock("telemetry.alerts")
        self._states: dict[str, _RuleState] = {}
        # direct listeners (subscribe()): the trigger-bus seam — the
        # online trainer hangs its refit trigger here. Delivered after
        # the engine lock is released, alongside the sink emits
        self._listeners: list[Any] = []
        # where rule series are sampled from: anything with a
        # ``peek(name, labels)`` returning an object carrying
        # ``kind``/``value`` (the process Registry, or the fleet
        # aggregator's merged-series view). None = the process-wide
        # registry, read at evaluate time.
        self._registry = registry
        for r in rules:
            self.add_rule(r)

    def add_rule(self, rule: AlertRule | dict) -> AlertRule:
        if isinstance(rule, dict):
            rule = AlertRule.from_dict(rule)
        with self._lock:
            if rule.name in self._states:
                raise ValueError(
                    f"alert rule {rule.name!r} already installed"
                )
            self._states[rule.name] = _RuleState(rule)
        return rule

    def rules(self) -> tuple[AlertRule, ...]:
        with self._lock:
            return tuple(st.rule for st in self._states.values())

    # -- the trigger bus -----------------------------------------------

    def subscribe(self, listener) -> None:
        """Register a callable receiving every ``alert_fired`` /
        ``alert_resolved`` event this engine emits — the trigger-bus
        seam the online trainer (``online/trainer.py``) subscribes
        its refit trigger to. Listeners run AFTER the engine lock is
        released (a listener may re-enter the engine — ``state()``
        from a trainer transcript is fine) and exceptions are
        isolated: one broken consumer must not unhook alerting for
        everyone else (warned, not raised)."""
        if not callable(listener):
            raise TypeError(f"listener must be callable, got "
                            f"{type(listener).__name__}")
        with self._lock:
            if listener not in self._listeners:
                self._listeners.append(listener)

    def unsubscribe(self, listener) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def _notify(self, events: list[dict]) -> None:
        if not events:
            return
        with self._lock:
            listeners = list(self._listeners)
        for ev in events:
            for fn in listeners:
                try:
                    fn(ev)
                except Exception as e:  # noqa: BLE001 — isolation, see
                    # subscribe(): alert delivery must survive one
                    # broken consumer
                    import warnings

                    warnings.warn(
                        f"alert listener {fn!r} raised {e!r}; event "
                        f"{ev.get('kind')}/{ev.get('rule')} dropped "
                        "for that listener only",
                        RuntimeWarning,
                        stacklevel=2,
                    )

    # -- sampling ------------------------------------------------------

    def _read_series(self, rule: AlertRule) -> float | None:
        """Current value of the rule's series, or None when there is
        nothing to sample: the series was never written (absent data
        is 'no evidence' — it must NOT read as 0.0, or an ``op "<"``
        rule would page on a service that served no traffic), or it
        exists under the wrong metric kind for the rule (a value rule
        aimed at a histogram must not poison the whole pass)."""
        reg = self._registry if self._registry is not None \
            else STATE.registry
        metric = reg.peek(rule.series, rule.labels)
        if metric is None:
            return None
        want = "counter" if rule.kind == "rate" else "gauge"
        if metric.kind != want:
            return None
        return float(metric.value)

    @staticmethod
    def _breach_value(st: _RuleState, now: float, window: float) -> bool:
        """Every sample in the window breaches, and the engine has
        watched at least that long."""
        if st.t_first is None or now - st.t_first < window:
            return False
        seen = False
        for t, v in reversed(st.samples):
            if t < now - window:
                break
            seen = True
            if not st.rule.breaches(v):
                return False
        return seen

    @staticmethod
    def _breach_rate(st: _RuleState, now: float, window: float) -> bool:
        """The counter's per-second rate over the window breaches.
        Anchored at the latest sample at or before the window start —
        absent one, there is no honest rate yet."""
        anchor: tuple[float, float] | None = None
        for t, v in st.samples:
            if t <= now - window:
                anchor = (t, v)
            else:
                break
        if anchor is None or not st.samples:
            return False
        t_now, v_now = st.samples[-1]
        dt = t_now - anchor[0]
        if dt <= 0:
            return False
        return st.rule.breaches((v_now - anchor[1]) / dt)

    # -- the tick ------------------------------------------------------

    def evaluate(self, now: float | None = None) -> list[dict]:
        """One evaluation pass over every rule; returns the events
        emitted (``alert_fired`` / ``alert_resolved``). ``now``
        defaults to the monotonic clock; inject a virtual clock for
        deterministic replay."""
        if now is None:
            now = time.monotonic()
        events: list[dict] = []
        counters: list[tuple[str, dict | None]] = []
        with self._lock:
            for st in self._states.values():
                rule = st.rule
                v = self._read_series(rule)
                st.last_value = v
                st.last_eval = now
                if v is None:
                    continue  # kind-mismatched series: no sample
                if st.t_first is None:
                    st.t_first = now
                st.samples.append((now, v))
                # prune: keep the slow window plus ONE older sample
                # (rate anchor); bounded regardless of tick cadence
                cutoff = now - rule.slow_window_s
                while (len(st.samples) >= 2
                       and st.samples[1][0] <= cutoff):
                    st.samples.popleft()
                breach_fn = (self._breach_rate if rule.kind == "rate"
                             else self._breach_value)
                breach = (breach_fn(st, now, rule.fast_window_s)
                          and breach_fn(st, now, rule.slow_window_s))
                if breach and not st.active:
                    if (st.last_fired is not None
                            and now - st.last_fired < rule.cooldown_s):
                        st.suppressed += 1
                        counters.append((
                            "sbt_alerts_suppressed_total",
                            {"rule": rule.name},
                        ))
                    else:
                        st.active = True
                        st.last_fired = now
                        st.fired += 1
                        counters.append((
                            "sbt_alerts_fired_total",
                            {"rule": rule.name},
                        ))
                        # stamped HERE, not in the emit path: consumers
                        # that hold the event itself (the fleet
                        # aggregator's incident log) need the wall
                        # clock even when no sink is subscribed
                        events.append({
                            "kind": "alert_fired",
                            "ts": time.time(),
                            "rule": rule.name,
                            "series": rule.series,
                            "value": v,
                            "threshold": rule.threshold,
                            "op": rule.op,
                            "severity": rule.severity,
                            "windows_s": [rule.fast_window_s,
                                          rule.slow_window_s],
                            "description": rule.description,
                            "now": now,
                        })
                elif st.active and not (
                    self._breach_rate(st, now, rule.fast_window_s)
                    if rule.kind == "rate" else rule.breaches(v)
                ):
                    # the incident is over. Value rules resolve on a
                    # clean LATEST sample; rate rules must re-evaluate
                    # the windowed rate — the raw cumulative counter
                    # value never falls back under a per-second
                    # threshold, so comparing it directly would leave
                    # the alert active forever after one burst (and an
                    # active rule cannot re-fire, swallowing every
                    # later genuine incident)
                    st.active = False
                    st.resolved += 1
                    counters.append((
                        "sbt_alerts_resolved_total",
                        {"rule": rule.name},
                    ))
                    events.append({
                        "kind": "alert_resolved",
                        "ts": time.time(),
                        "rule": rule.name,
                        "series": rule.series,
                        "value": v,
                        "severity": rule.severity,
                        "now": now,
                    })
            n_active = sum(1 for st in self._states.values()
                           if st.active)
        if STATE.enabled:
            reg = STATE.registry
            reg.inc("sbt_alerts_evaluations_total")
            reg.set("sbt_alerts_active", float(n_active))
            for name, labels in counters:
                reg.inc(name, 1.0, labels)
        # emit AFTER releasing the engine lock: an alert_fired event
        # triggers the flight recorder, whose dump snapshots the
        # registry and writes a file — none of that belongs under the
        # lock the next evaluate() needs
        for ev in events:
            _emit(ev)
        self._notify(events)
        return events

    # -- introspection -------------------------------------------------

    def active(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(
                name for name, st in self._states.items() if st.active
            ))

    def state(self) -> dict[str, Any]:
        """JSON digest for ``/alerts``."""
        with self._lock:
            rules = []
            for st in self._states.values():
                rules.append({
                    **st.rule.to_dict(),
                    "active": st.active,
                    "fired": st.fired,
                    "resolved": st.resolved,
                    "suppressed": st.suppressed,
                    "last_value": st.last_value,
                    "last_eval": st.last_eval,
                    "last_fired": st.last_fired,
                })
            return {
                "rules": rules,
                "active": sorted(
                    name for name, st in self._states.items()
                    if st.active
                ),
            }


def default_drift_rules(
    *,
    psi_threshold: float = 0.5,
    confidence_psi_threshold: float = 0.5,
    fast_window_s: float = 30.0,
    slow_window_s: float = 300.0,
    cooldown_s: float = 300.0,
    labels: dict[str, Any] | None = None,
    name_prefix: str = "",
) -> list[AlertRule]:
    """The starter rule set for the quality plane: feature drift
    (``sbt_quality_psi_max``) and prediction-confidence drift
    (``sbt_quality_confidence_psi``). ``labels`` must match the
    monitor's gauge labels — ``{"model": name}`` for a monitor
    attached via ``ModelRegistry.enable_quality(name)`` (its
    ``monitor.labels``), omitted for an anonymous executor's monitor.
    ``name_prefix`` disambiguates rule names when installing the set
    once per model."""
    return [
        AlertRule(
            f"{name_prefix}feature-drift", "sbt_quality_psi_max",
            labels=labels,
            threshold=psi_threshold, kind="value", op=">",
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            cooldown_s=cooldown_s,
            description="live feature distribution no longer matches "
                        "the training reference (max per-feature PSI)",
        ),
        AlertRule(
            f"{name_prefix}confidence-drift",
            "sbt_quality_confidence_psi", labels=labels,
            threshold=confidence_psi_threshold, kind="value", op=">",
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            cooldown_s=cooldown_s,
            description="served confidence distribution no longer "
                        "matches the OOB reference",
        ),
    ]


def default_capacity_rules(
    *,
    headroom_threshold: float = 0.1,
    eviction_rate_threshold: float = 1.0,
    tail_p99_threshold_ms: float = 250.0,
    quota_shed_rate_threshold: float = 1.0,
    fast_window_s: float = 30.0,
    slow_window_s: float = 300.0,
    cooldown_s: float = 300.0,
    labels: dict[str, Any] | None = None,
    name_prefix: str = "",
    tenancy: bool = True,
) -> list[AlertRule]:
    """The starter rule set for the capacity plane [ISSUE 16], reading
    the gauges ``telemetry.capacity`` refreshes on every scrape:

    - **capacity-headroom-low** — the program cache's free-slot ratio
      fell below ``headroom_threshold``: the next cold model admission
      evicts someone;
    - **capacity-cold-model-resident** — entries owned by cold-class
      models are resident while headroom is being consumed — the
      reclaim candidates a residency policy would take first;
    - **capacity-eviction-churn** — sustained eviction burn rate above
      ``eviction_rate_threshold``/s: the cache capacity sits below the
      working set and compiles are being re-paid (the thrash signal
      the ``cache-churn`` drill manufactures deliberately).

    With ``tenancy=True`` (default) the tenant-aware variants
    [ISSUE 17] ride along, reading the series the tenancy plane
    exports (absent series never fire — a process with no fleet pays
    nothing for carrying the rules):

    - **tenancy-tail-latency-burn** — the tail tenants' p99
      (``sbt_tenancy_tail_p99_ms``, everyone but the Zipf head) burned
      above ``tail_p99_threshold_ms`` across both windows: the fleet
      is serving its head at the tail's expense;
    - **tenancy-quota-shed-rate** — sustained admission sheds above
      ``quota_shed_rate_threshold``/s: quotas/priorities are actively
      rejecting traffic, not just backstopping a burst;
    - **tenancy-pin-violation** — a residency/cache eviction had to
      sacrifice a hot-pinned tenant: the residency budget (or cache
      capacity) is smaller than the hot set;
    - **tenancy-quarantine-flapping** — two or more quarantine trips
      (``sbt_tenant_quarantine_trips_total``) inside the fast window
      [ISSUE 18]: a tenant is cycling trip → probe → re-trip instead
      of recovering, so its backoff ladder (or the underlying fault)
      needs an operator.
    """
    tenancy_rules = [
        AlertRule(
            f"{name_prefix}tenancy-tail-latency-burn",
            "sbt_tenancy_tail_p99_ms", labels=labels,
            threshold=tail_p99_threshold_ms, kind="value", op=">",
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            cooldown_s=cooldown_s,
            description="tail-tenant p99 latency burning above "
                        "threshold: the fleet serves its head at the "
                        "tail's expense",
        ),
        AlertRule(
            f"{name_prefix}tenancy-quota-shed-rate",
            "sbt_tenancy_shed_total", labels=labels,
            threshold=quota_shed_rate_threshold, kind="rate", op=">",
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            cooldown_s=cooldown_s,
            description="sustained admission shed rate: quotas/"
                        "priorities rejecting steady traffic, not a "
                        "burst",
        ),
        AlertRule(
            f"{name_prefix}tenancy-pin-violation",
            "sbt_tenancy_pin_violations_total", labels=labels,
            threshold=0.0, kind="rate", op=">",
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            cooldown_s=cooldown_s,
            description="hot-pinned tenants being evicted: the "
                        "residency budget is smaller than the hot set",
        ),
        AlertRule(
            f"{name_prefix}tenancy-quarantine-flapping",
            "sbt_tenant_quarantine_trips_total", labels=labels,
            # ≥2 trips inside the fast window, expressed as the burn
            # rate the engine evaluates (strictly above 1.5 trips per
            # fast window tolerates no flapping but ignores a single
            # contained trip-and-recover)
            threshold=1.5 / fast_window_s, kind="rate", op=">",
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            cooldown_s=cooldown_s,
            description="quarantine flapping: a tenant is cycling "
                        "trip/probe/re-trip instead of recovering",
        ),
    ] if tenancy else []
    return [
        AlertRule(
            f"{name_prefix}capacity-headroom-low",
            "sbt_capacity_cache_headroom_ratio", labels=labels,
            threshold=headroom_threshold, kind="value", op="<",
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            cooldown_s=cooldown_s,
            description="program-cache free-slot ratio below "
                        "threshold: the next admission evicts",
        ),
        AlertRule(
            f"{name_prefix}capacity-cold-model-resident",
            "sbt_capacity_cold_resident_entries", labels=labels,
            threshold=0.0, kind="value", op=">",
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            cooldown_s=cooldown_s,
            description="cold-demand models hold resident cache "
                        "entries — reclaimable bytes",
        ),
        AlertRule(
            f"{name_prefix}capacity-eviction-churn",
            "sbt_program_cache_evictions_total", labels=labels,
            threshold=eviction_rate_threshold, kind="rate", op=">",
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            cooldown_s=cooldown_s,
            description="sustained program-cache eviction burn rate: "
                        "capacity below the working set, compiles "
                        "being re-paid",
        ),
    ] + tenancy_rules


# -- process default ----------------------------------------------------

_default: AlertEngine | None = None
# concurrent first installs must not each build an engine — the loser
# would evaluate a detached rule set nobody can see on /alerts
_default_lock = make_lock("telemetry.alerts.default")


def install(rules=()) -> AlertEngine:
    """Install rules on the process-default engine (created on first
    call) — what ``/alerts`` serves and evaluates on every scrape."""
    global _default
    with _default_lock:
        if _default is None:
            _default = AlertEngine()
        eng = _default
    for r in rules:
        eng.add_rule(r)
    return eng


def get() -> AlertEngine | None:
    """The process-default engine, if one was ever installed."""
    return _default


def uninstall() -> None:
    """Drop the process-default engine (test isolation; embedders
    rebuilding their rule set)."""
    global _default
    with _default_lock:
        _default = None
