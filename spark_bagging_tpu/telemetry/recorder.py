"""Failure flight recorder — a post-mortem artifact for serving faults.

Metrics say *that* something went wrong; the flight recorder preserves
*what was happening when it did*. Armed, it subscribes to the process
event stream (every span, fit report, and serving fault event) into a
bounded ring buffer, and on a trigger event atomically writes
``flight_<ts>_<seq>.json`` into the telemetry dir containing:

- the trigger event itself (with its ``trace_id``/``links``, so the
  failing request is resolvable in the captured window);
- the last ``capacity`` events (the ring — enqueue/batch/forward/
  scatter spans of the traffic leading up to the fault);
- a full metrics-registry snapshot (queue depth, overload counts,
  latency histograms with quantiles at the moment of failure);
- held-lock state across all threads plus any recorded lock-order
  violations (``analysis.locks`` — populated when ``SBT_LOCK_DEBUG``
  is armed, empty otherwise).

Triggers (event ``kind``):

- ``serving_batch_error`` — an executor forward failed a micro-batch;
- ``swap_rejected`` — a hot-swap failed contract validation;
- ``alert_fired`` — the quality plane's alert engine tripped a rule
  (drift, burn rate — see ``telemetry/alerts.py``);
- ``serving_overloaded`` — only as a BURST: ``burst_threshold``
  rejections inside ``burst_window_s`` (a single shed request is
  backpressure working as designed; a burst is an incident).

A per-kind ``cooldown_s`` guarantees one dump per incident, not one
per failing request (``sbt_flight_dumps_suppressed_total`` counts the
suppressed ones). The ring costs one deque append per event and is
only subscribed while armed — the disabled serving hot path never
sees it. Starting the exposition server (``telemetry.server``) arms
the default recorder so ``/debug/spans`` has a window to serve.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any

from spark_bagging_tpu.analysis.locks import make_lock

DUMP_SCHEMA_VERSION = 1

# event kinds that dump immediately (one incident = one event);
# alert_fired is the quality plane's contribution — an alert arrives
# with the black box of the traffic that tripped it; the PR-11 fault
# plane adds worker crash loops, pre-commit swap failures, and lost
# serving shards (each per-kind cooldown'd to one dump per incident)
TRIGGER_KINDS = ("serving_batch_error", "swap_rejected", "alert_fired",
                 "serving_crash_loop", "swap_failed",
                 "serving_shard_failed", "refit_rejected")
# event kind that dumps only as a burst
BURST_KIND = "serving_overloaded"

# event kinds the fleet incident timeline collects from each peer's
# ring: every dump trigger, the overload bursts, and the swap/refit
# commits (not incidents themselves, but the events incidents
# correlate WITH — "did that flight dump land right after peer 2's
# rolling swap?")
TIMELINE_KINDS = TRIGGER_KINDS + (BURST_KIND, "model_swapped",
                                  "refit_published")


# sbt-lint: shared-state
class FlightRecorder:
    """Bounded event ring + trigger-driven atomic JSON dumps.

    Implements the sink protocol (``emit(event)``) and attaches to the
    process-wide event stream via :meth:`arm`. All knobs are
    constructor arguments; the module-level :func:`arm` manages a
    process default instance.
    """

    def __init__(
        self,
        *,
        capacity: int = 2048,
        dir: str | None = None,
        burst_threshold: int = 10,
        burst_window_s: float = 1.0,
        cooldown_s: float = 30.0,
    ) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if burst_threshold < 1:
            # 0 would make the burst check index an empty deque (the
            # deque's maxlen) and raise from inside emit(); "dump on
            # every shed" is burst_threshold=1
            raise ValueError(
                f"burst_threshold must be >= 1, got {burst_threshold}"
            )
        self.capacity = int(capacity)
        self.dir = dir
        self.burst_threshold = int(burst_threshold)
        self.burst_window_s = float(burst_window_s)
        self.cooldown_s = float(cooldown_s)
        self._lock = make_lock("telemetry.recorder")
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._overload_ts: deque[float] = deque(maxlen=self.burst_threshold)
        self._last_dump_ts: dict[str, float] = {}
        self._seq = 0
        self._armed = False
        self.dumps: list[str] = []  # paths written, in order
        # compact per-dump records (path, ts, trigger kind + handle):
        # what a fleet aggregator scrapes to place this peer's dumps on
        # the correlated incident timeline without re-reading the files
        self.dump_records: list[dict] = []

    # -- sink protocol -------------------------------------------------

    def emit(self, event: dict) -> None:
        """Record one event; dump if it is (or completes) a trigger."""
        if event.get("kind") == "serving_request":
            # the per-request arrival stream (workload capture, PR 6)
            # is the highest-rate event in the process and carries no
            # forensic value the enqueue span doesn't: ringing it
            # would evict the span/error window — the thing a flight
            # dump exists to preserve — in under a second of real
            # traffic. Workload recorders subscribe separately.
            return
        trigger: dict | None = None
        with self._lock:
            self._ring.append(event)
            kind = event.get("kind")
            now = time.monotonic()
            if kind in TRIGGER_KINDS:
                trigger = event if self._pass_cooldown(kind, now) else None
            elif kind == BURST_KIND:
                self._overload_ts.append(now)
                burst = (
                    len(self._overload_ts) >= self.burst_threshold
                    and now - self._overload_ts[0] <= self.burst_window_s
                )
                if burst and self._pass_cooldown(kind, now):
                    trigger = event
        if trigger is not None:
            try:
                self.dump(trigger)
            except Exception as e:  # noqa: BLE001 — a failed black-box
                # write (read-only FS, disk full, bad SBT_TELEMETRY_DIR)
                # must not propagate into the serving threads that
                # emitted the trigger: it would kill the batcher worker
                # or surface to clients in place of Overloaded
                import warnings

                # give back the cooldown window the trigger consumed —
                # otherwise one transient write failure silences every
                # further trigger of this kind for cooldown_s and the
                # incident yields zero artifacts
                with self._lock:
                    self._last_dump_ts.pop(trigger.get("kind"), None)
                warnings.warn(
                    f"flight recorder failed to write a dump: {e!r}",
                    RuntimeWarning,
                    stacklevel=2,
                )

    def _pass_cooldown(self, kind: str, now: float) -> bool:
        """Under the ALREADY-HELD lock: one dump per incident window."""
        last = self._last_dump_ts.get(kind)
        if last is not None and now - last < self.cooldown_s:
            from spark_bagging_tpu.telemetry.state import STATE

            if STATE.enabled:
                STATE.registry.inc("sbt_flight_dumps_suppressed_total")
            return False
        # sbt-lint: disable=shared-state-unlocked — every caller holds self._lock (the _pass_cooldown contract)
        self._last_dump_ts[kind] = now
        return True

    # -- introspection -------------------------------------------------

    def events(self, kind: str | None = None, limit: int | None = None):
        """Snapshot of the ring (oldest first), optionally filtered by
        event kind and truncated to the most recent ``limit``."""
        with self._lock:
            out = list(self._ring)
        if kind is not None:
            out = [e for e in out if e.get("kind") == kind]
        if limit is not None:
            out = out[-limit:]
        return out

    # -- the dump ------------------------------------------------------

    def dump(self, trigger: dict | None = None) -> str:
        """Atomically write the black box to ``flight_<ts>_<seq>.json``
        (write-then-rename: a scraper or operator never sees a torn
        file) and return its path. Callable manually for an on-demand
        snapshot; normally driven by :meth:`emit` triggers."""
        from spark_bagging_tpu.analysis import locks
        from spark_bagging_tpu.telemetry.sinks import telemetry_dir
        from spark_bagging_tpu.telemetry.state import STATE

        with self._lock:
            events = list(self._ring)
            self._seq += 1
            seq = self._seq
        payload: dict[str, Any] = {
            "schema": DUMP_SCHEMA_VERSION,
            "ts": time.time(),
            "pid": os.getpid(),
            "trigger": trigger,
            "n_events": len(events),
            "events": events,
            "metrics": STATE.registry.snapshot(quantiles=True),
            "locks": {
                "held": {
                    t: list(names)
                    for t, names in locks.all_held_locks().items()
                },
                "violations": locks.violations(),
                "edges": [list(e) for e in locks.acquisition_edges()],
            },
        }
        base = self.dir or telemetry_dir()
        os.makedirs(base, exist_ok=True)
        path = os.path.join(
            base, f"flight_{int(payload['ts'] * 1000)}_{seq}.json"
        )
        tmp = path + ".tmp"
        # synchronous by design: the black box must be on disk before
        # the triggering thread moves on (a crashing process cannot be
        # asked to finish a background write). No fsync — it would
        # charge a loaded host's full disk queue to the batcher worker
        # or an overloaded client's submit(); rename-visibility and
        # surviving a PROCESS crash need only the page cache
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
        os.replace(tmp, path)
        record = {
            "path": path,
            "ts": payload["ts"],
            "seq": seq,
            "kind": (trigger or {}).get("kind") or "manual",
        }
        # the trigger's correlation handle, when it carries one: the
        # alert rule, the model a swap died on, the failing trace
        for key in ("rule", "model", "trace_id"):
            v = (trigger or {}).get(key)
            if v is not None:
                record[key] = v
        with self._lock:
            self.dumps.append(path)
            self.dump_records.append(record)
        if STATE.enabled:
            STATE.registry.inc("sbt_flight_dumps_total")
        return path

    def timeline_feed(self, *, dumps: int = 32,
                      events: int = 64) -> dict[str, list[dict]]:
        """The peer-side incident feed: the most recent dump records
        plus the ring's timeline-relevant events (dump triggers,
        overload bursts, swap commits). ``/varz`` exposes it as the
        ``flight`` section, which is what the fleet aggregator's
        ``/fleet/incidents`` correlation consumes."""
        with self._lock:
            recs = list(self.dump_records[-dumps:])
            ring = list(self._ring)
        evs = [e for e in ring if e.get("kind") in TIMELINE_KINDS]
        return {"dumps": recs, "events": evs[-events:]}

    # -- lifecycle -----------------------------------------------------

    def arm(self) -> "FlightRecorder":
        """Subscribe to the process event stream (idempotent)."""
        from spark_bagging_tpu.telemetry.state import STATE

        with self._lock:
            already = self._armed
            self._armed = True
        if not already:
            STATE.add_sink(self)
        return self

    def disarm(self) -> None:
        from spark_bagging_tpu.telemetry.state import STATE

        with self._lock:
            was = self._armed
            self._armed = False
        if was:
            STATE.remove_sink(self)

    @property
    def armed(self) -> bool:
        return self._armed


_default: FlightRecorder | None = None
# guards _default creation: concurrent first arms (a thread calling
# arm() while start_server() arms on another) must not each construct
# and subscribe a recorder — the loser would be an undetachable sink
# writing duplicate dumps
_default_lock = make_lock("telemetry.recorder.default")


def arm(**kwargs: Any) -> FlightRecorder:
    """Arm the process-default recorder (creating it on first call;
    ``kwargs`` are :class:`FlightRecorder` options and only apply at
    creation). The exposition server calls this on start — so under
    ``SBT_METRICS_PORT`` the default recorder already exists with
    default knobs, and a later ``arm(cooldown_s=...)`` cannot retune
    it; that case warns instead of silently dropping the options."""
    global _default
    with _default_lock:
        if _default is None:
            _default = FlightRecorder(**kwargs)
        elif kwargs:
            import warnings

            warnings.warn(
                "flight recorder is already created; arm() options "
                f"{sorted(kwargs)} are ignored (construct "
                "FlightRecorder directly, or disarm and drop the "
                "default first)",
                RuntimeWarning,
                stacklevel=2,
            )
        rec = _default
    return rec.arm()


def disarm() -> None:
    """Detach the process-default recorder from the event stream."""
    if _default is not None:
        _default.disarm()


def get() -> FlightRecorder | None:
    """The process-default recorder, if one was ever armed."""
    return _default
