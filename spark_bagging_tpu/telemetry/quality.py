"""Model-quality plane: streaming drift detection over live traffic.

The observability plane through PR 6 watches the *system* — latency,
compiles, padding, overloads — but is blind to the *model*: nothing
says whether live traffic still looks like the data the bag was fitted
on, or whether the ensemble still agrees with itself. This module is
the model half, in three pieces:

1. **Reference profile** (:class:`ReferenceProfile`) — a fixed-size,
   JSON-friendly summary of the training distribution computed at fit
   time (``bagging.py`` stores it as ``estimator.quality_profile_``
   and checkpoints round-trip it): per-feature decile bin edges +
   fractions, the encoded class distribution, a confidence histogram
   (populated from the OOB decision function when ``oob_score`` ran —
   the honest held-out confidence), and, for regressors, a target
   histogram. Memory is ``O(n_features × bins)`` floats — independent
   of training size (rows are strided down to ``max_rows`` for the
   quantile pass).

2. **Live sketches** (:class:`QualityMonitor`) — fixed-memory
   streaming state fed from the serving hot path
   (``EnsembleExecutor._forward_packed``, which underlies BOTH
   dispatch paths: the coalescing worker's ``forward_parts`` and the
   PR-7 direct-dispatch inline serve). Per feature: counts in the
   reference's bins (order-independent — the replay determinism gate
   leans on this), a running moment sketch, and a P² quantile sketch
   (Jain & Chlamtac: five markers per quantile, O(1) memory and
   update) fed with a deterministic per-batch row stride. Per
   prediction: class counts and a confidence (max-probability)
   histogram with its own P² median. Total memory is
   ``O(n_features × bins)`` — a million served rows cost the same
   bytes as a thousand.

3. **Drift scores** — PSI (population stability index) and a binned
   KS statistic per feature against the reference, plus
   prediction-class and confidence PSI, recomputed every
   ``refresh_every`` rows and exported as ``sbt_quality_*`` gauges
   (per-feature series capped at ``export_feature_limit`` to bound
   scrape cardinality; the aggregates always export). The alert
   engine (:mod:`~spark_bagging_tpu.telemetry.alerts`) rules over
   those gauges; ``/debug/drift`` serves :func:`debug_summary`.

**Ensemble disagreement** rides along: bagging's replica spread is a
free uncertainty signal the vote/mean aggregation throws away
(*Reproducible Model Selection Using Bagged Posteriors*, arXiv
2007.14845). The executor samples a configurable fraction of batches
through a per-replica-preserving forward (``model.replica_forward()``,
compiled separately per bucket — counted in
``sbt_quality_disagreement_compiles_total``, NOT in the serving
compile counter, so the zero-post-warmup-compile gate is untouched)
and feeds :func:`disagreement_stats` here. Served outputs stay
bitwise-identical: the tap is purely additional compute.

Cost contract: **zero overhead when disabled**. No monitor attached
means the executor's gate is one attribute read (``self._quality is
None``); nothing in this module runs. Everything mutable in a monitor
sits behind one ``make_lock`` (the PR-4 lock-order detector sees it),
and the only lock taken while holding it is the telemetry registry's
(quality → registry, the same direction every exporter uses).
"""

from __future__ import annotations

import math
import time
import weakref
from typing import Any

import numpy as np

from spark_bagging_tpu.analysis.locks import make_lock
from spark_bagging_tpu.telemetry.state import STATE

PROFILE_SCHEMA_VERSION = 1

#: Fraction floor for PSI smoothing: an empty bin contributes through
#: this epsilon instead of dividing by zero (standard PSI practice).
PSI_EPS = 1e-4

#: Fixed confidence-histogram bin count on [0, 1] — fixed (not
#: data-derived) so a profile saved without a confidence reference can
#: still gain one later from OOB scores with compatible edges.
CONFIDENCE_BINS = 20


# -- sketch primitives --------------------------------------------------

class P2Quantile:
    """Jain & Chlamtac's P² streaming quantile estimator: five markers,
    O(1) memory and per-update cost, no stored samples. Exact for the
    first five observations; afterwards the markers drift toward the
    target quantile via piecewise-parabolic interpolation. Order-
    dependent by construction — drift SCORES therefore come from the
    order-independent binned counts, and P² values are telemetry."""

    __slots__ = ("q", "_n", "_heights", "_pos", "_want")

    def __init__(self, q: float) -> None:
        if not 0.0 < q < 1.0:
            raise ValueError(f"q must be in (0, 1), got {q}")
        self.q = float(q)
        self._n = 0
        self._heights: list[float] = []
        self._pos = [1.0, 2.0, 3.0, 4.0, 5.0]
        self._want = [1.0, 1 + 2 * q, 1 + 4 * q, 3 + 2 * q, 5.0]

    def update(self, v: float) -> None:
        v = float(v)
        self._n += 1
        h = self._heights
        if len(h) < 5:
            h.append(v)
            h.sort()
            return
        # locate the cell; clamp outliers into the end markers
        if v < h[0]:
            h[0] = v
            k = 0
        elif v >= h[4]:
            h[4] = v
            k = 3
        else:
            k = 0
            while k < 3 and v >= h[k + 1]:
                k += 1
        for i in range(k + 1, 5):
            self._pos[i] += 1.0
        # desired positions are linear in n — rebuild from the formula
        n = float(self._n)
        self._want = [
            1.0,
            1 + (n - 1) * self.q / 2,
            1 + (n - 1) * self.q,
            1 + (n - 1) * (1 + self.q) / 2,
            n,
        ]
        for i in (1, 2, 3):
            d = self._want[i] - self._pos[i]
            if (d >= 1 and self._pos[i + 1] - self._pos[i] > 1) or (
                    d <= -1 and self._pos[i - 1] - self._pos[i] < -1):
                s = 1.0 if d >= 1 else -1.0
                hp = self._parabolic(i, s)
                if not h[i - 1] < hp < h[i + 1]:
                    hp = self._linear(i, s)
                h[i] = hp
                self._pos[i] += s

    def _parabolic(self, i: int, s: float) -> float:
        h, p = self._heights, self._pos
        return h[i] + s / (p[i + 1] - p[i - 1]) * (
            (p[i] - p[i - 1] + s) * (h[i + 1] - h[i]) / (p[i + 1] - p[i])
            + (p[i + 1] - p[i] - s) * (h[i] - h[i - 1]) / (p[i] - p[i - 1])
        )

    def _linear(self, i: int, s: float) -> float:
        h, p = self._heights, self._pos
        j = i + int(s)
        return h[i] + s * (h[j] - h[i]) / (p[j] - p[i])

    def value(self) -> float:
        """Current estimate (exact below five samples; NaN when empty)."""
        h = self._heights
        if not h:
            return math.nan
        if len(h) < 5:
            srt = sorted(h)
            # nearest-rank on the exact small sample
            k = min(len(srt) - 1, int(self.q * len(srt)))
            return srt[k]
        return h[2]


class MomentSketch:
    """Vectorized running moments over ``d`` parallel streams: count,
    sum, sum of squares, min, max — one numpy op per batch, fixed
    memory."""

    __slots__ = ("count", "_sum", "_sumsq", "_min", "_max")

    def __init__(self, d: int) -> None:
        self.count = 0
        self._sum = np.zeros(d, np.float64)
        self._sumsq = np.zeros(d, np.float64)
        self._min: np.ndarray | None = None
        self._max: np.ndarray | None = None

    def update(self, X: np.ndarray) -> None:
        """Fold a ``(n, d)`` batch in."""
        X64 = X.astype(np.float64, copy=False)
        self.count += X.shape[0]
        self._sum += X64.sum(axis=0)
        self._sumsq += (X64 * X64).sum(axis=0)
        lo, hi = X64.min(axis=0), X64.max(axis=0)
        self._min = lo if self._min is None else np.minimum(self._min, lo)
        self._max = hi if self._max is None else np.maximum(self._max, hi)

    def mean(self) -> np.ndarray:
        if self.count == 0:
            return np.full_like(self._sum, np.nan)
        return self._sum / self.count

    def std(self) -> np.ndarray:
        if self.count == 0:
            return np.full_like(self._sum, np.nan)
        var = self._sumsq / self.count - self.mean() ** 2
        return np.sqrt(np.maximum(var, 0.0))


def bin_counts(values: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Counts of ``values`` in the ``len(edges)+1`` bins the internal
    ``edges`` cut the line into. ``side="right"`` on BOTH the reference
    fractions and the live counts, so PSI compares like with like."""
    idx = np.searchsorted(np.asarray(edges, np.float64),
                          np.asarray(values, np.float64), side="right")
    return np.bincount(idx, minlength=len(edges) + 1).astype(np.int64)


def psi(ref_fractions, live_counts) -> float:
    """Population stability index between a reference fraction vector
    and live bin counts (same binning). Zero when the live stream is
    empty — no evidence is not drift.

    Live fractions get add-half (Laplace) smoothing: with a raw
    epsilon floor, every not-yet-populated bin of a small live sample
    contributes ``≈ 0.1·ln(0.1/eps)`` of pure noise — a few hundred
    in-distribution rows scored PSI > 2 that way. Smoothing scales the
    empty-bin penalty with the evidence (``0.5/(n + k/2)``), so the
    score converges to the true PSI as rows accumulate instead of
    starting at a cliff. The reference side (a full training pass) only
    needs the :data:`PSI_EPS` floor against log-zero."""
    live_counts = np.asarray(live_counts, np.float64)
    total = live_counts.sum()
    if total <= 0:
        return 0.0
    k = len(live_counts)
    live = (live_counts + 0.5) / (total + 0.5 * k)
    ref = np.clip(np.asarray(ref_fractions, np.float64), PSI_EPS, None)
    ref /= ref.sum()
    return float(((live - ref) * np.log(live / ref)).sum())


def ks_stat(ref_fractions, live_counts) -> float:
    """Binned two-sample KS statistic: the max CDF gap at the shared
    bin edges (a lower bound on the continuous KS — honest for a
    fixed-memory sketch). Zero on an empty live stream."""
    live_counts = np.asarray(live_counts, np.float64)
    total = live_counts.sum()
    if total <= 0:
        return 0.0
    live = np.cumsum(live_counts / total)
    ref = np.cumsum(np.asarray(ref_fractions, np.float64))
    return float(np.abs(live - ref).max())


# -- the fit-time reference ---------------------------------------------

class ReferenceProfile:
    """What "normal" looked like at fit time — the drift comparand.

    Built by :meth:`from_training` (``bagging.py`` calls it at the end
    of every in-memory fit), serialized via :meth:`to_dict` into the
    checkpoint manifest (``utils/checkpoint.py``), so
    ``ModelRegistry.save()/load()`` round-trips it with the weights.
    """

    def __init__(
        self,
        *,
        task: str,
        n_features: int,
        feature_edges: list[list[float]],
        feature_fractions: list[list[float]],
        class_fractions: list[float] | None = None,
        confidence_fractions: list[float] | None = None,
        prediction_edges: list[float] | None = None,
        prediction_fractions: list[float] | None = None,
        n_rows: int = 0,
        confidence_source: str | None = None,
    ) -> None:
        if task not in ("classification", "regression"):
            raise ValueError(f"unknown task {task!r}")
        if len(feature_edges) != n_features or \
                len(feature_fractions) != n_features:
            raise ValueError(
                f"profile carries {len(feature_edges)} feature edge "
                f"vectors for n_features={n_features}"
            )
        self.task = task
        self.n_features = int(n_features)
        self.feature_edges = [
            [float(e) for e in edges] for edges in feature_edges
        ]
        self.feature_fractions = [
            [float(f) for f in fr] for fr in feature_fractions
        ]
        self.class_fractions = (
            None if class_fractions is None
            else [float(f) for f in class_fractions]
        )
        self.confidence_fractions = (
            None if confidence_fractions is None
            else [float(f) for f in confidence_fractions]
        )
        self.prediction_edges = (
            None if prediction_edges is None
            else [float(e) for e in prediction_edges]
        )
        self.prediction_fractions = (
            None if prediction_fractions is None
            else [float(f) for f in prediction_fractions]
        )
        self.n_rows = int(n_rows)
        self.confidence_source = confidence_source

    # the fixed confidence grid (see CONFIDENCE_BINS)
    @staticmethod
    def confidence_edges() -> np.ndarray:
        return np.linspace(0.0, 1.0, CONFIDENCE_BINS + 1)[1:-1]

    @classmethod
    def from_training(
        cls,
        X,
        y=None,
        *,
        task: str,
        n_classes: int | None = None,
        bins: int = 10,
        max_rows: int = 4096,
    ) -> "ReferenceProfile":
        """Summarize the training set: per-feature decile edges and
        fractions (rows strided down to ``max_rows`` for the quantile
        pass — deterministic, no RNG), the encoded class distribution
        (classification, from ``y``), and a target histogram
        (regression, from ``y``). The confidence reference starts
        empty; :meth:`set_confidence_reference` fills it from OOB
        scores when available."""
        X = np.asarray(X, np.float32)
        if X.ndim != 2:
            raise ValueError(f"X must be 2-D, got shape {X.shape}")
        n, d = X.shape
        stride = max(1, -(-n // max_rows))  # ceil division
        Xs = np.asarray(X[::stride], np.float64)
        qs = np.arange(1, bins) / bins
        feature_edges: list[list[float]] = []
        feature_fractions: list[list[float]] = []
        for j in range(d):
            col = Xs[:, j]
            edges = np.quantile(col, qs)
            counts = bin_counts(col, edges)
            feature_edges.append([float(e) for e in edges])
            feature_fractions.append(
                [float(c) / len(col) for c in counts]
            )
        class_fractions = None
        prediction_edges = None
        prediction_fractions = None
        if y is not None:
            ys = np.asarray(y)
            if task == "classification":
                y_int = ys.astype(np.int64).ravel()
                c = int(n_classes if n_classes is not None
                        else y_int.max() + 1)
                counts = np.bincount(y_int, minlength=c)
                class_fractions = [
                    float(v) / len(y_int) for v in counts
                ]
            else:
                yf = ys.astype(np.float64).ravel()[::stride]
                edges = np.quantile(yf, qs)
                counts = bin_counts(yf, edges)
                prediction_edges = [float(e) for e in edges]
                prediction_fractions = [
                    float(c) / len(yf) for c in counts
                ]
        return cls(
            task=task, n_features=d,
            feature_edges=feature_edges,
            feature_fractions=feature_fractions,
            class_fractions=class_fractions,
            prediction_edges=prediction_edges,
            prediction_fractions=prediction_fractions,
            n_rows=n,
        )

    def set_confidence_reference(self, max_proba,
                                 source: str = "oob") -> None:
        """Install the held-out confidence histogram (per-row max
        probability — OOB decision-function rows when ``oob_score``
        ran: the honest estimate of served confidence)."""
        conf = np.asarray(max_proba, np.float64).ravel()
        conf = conf[np.isfinite(conf)]
        if conf.size == 0:
            return
        counts = bin_counts(conf, self.confidence_edges())
        self.confidence_fractions = [
            float(c) / conf.size for c in counts
        ]
        self.confidence_source = source

    def to_dict(self) -> dict[str, Any]:
        return {
            "schema": PROFILE_SCHEMA_VERSION,
            "task": self.task,
            "n_features": self.n_features,
            "n_rows": self.n_rows,
            "feature_edges": self.feature_edges,
            "feature_fractions": self.feature_fractions,
            "class_fractions": self.class_fractions,
            "confidence_fractions": self.confidence_fractions,
            "confidence_source": self.confidence_source,
            "prediction_edges": self.prediction_edges,
            "prediction_fractions": self.prediction_fractions,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ReferenceProfile":
        schema = d.get("schema")
        if schema != PROFILE_SCHEMA_VERSION:
            raise ValueError(
                f"quality profile schema {schema!r} not supported "
                f"(this build reads {PROFILE_SCHEMA_VERSION})"
            )
        return cls(
            task=d["task"], n_features=d["n_features"],
            feature_edges=d["feature_edges"],
            feature_fractions=d["feature_fractions"],
            class_fractions=d.get("class_fractions"),
            confidence_fractions=d.get("confidence_fractions"),
            prediction_edges=d.get("prediction_edges"),
            prediction_fractions=d.get("prediction_fractions"),
            n_rows=d.get("n_rows", 0),
            confidence_source=d.get("confidence_source"),
        )

    def __repr__(self) -> str:
        return (f"ReferenceProfile(task={self.task!r}, "
                f"n_features={self.n_features}, n_rows={self.n_rows})")


# -- disagreement -------------------------------------------------------

def disagreement_stats(rep_out: np.ndarray, task: str) -> dict[str, float]:
    """Ensemble-disagreement summary of one per-replica forward.

    ``rep_out`` is ``(R, n, C)`` per-replica probabilities
    (classification) or ``(R, n)`` per-replica predictions
    (regression). Classification disagreement is the mean fraction of
    replicas whose argmax differs from the soft-vote aggregate (the
    served answer); ``proba_std`` is the mean cross-replica std of the
    probabilities. Regression disagreement is the mean cross-replica
    prediction std (the bagged predictive spread)."""
    rep = np.asarray(rep_out, np.float64)
    if task == "classification":
        mean_proba = rep.mean(axis=0)            # (n, C) — the served agg
        agg = mean_proba.argmax(axis=-1)         # (n,)
        votes = rep.argmax(axis=-1)              # (R, n)
        agree = (votes == agg[None, :]).mean(axis=0)
        return {
            "disagreement": float(1.0 - agree.mean()),
            "proba_std": float(rep.std(axis=0).mean()),
            "rows": int(rep.shape[1]),
        }
    std = rep.std(axis=0)                        # (n,)
    return {
        "disagreement": float(std.mean()),
        "pred_std": float(std.mean()),
        "rows": int(rep.shape[1]),
    }


# -- the live monitor ---------------------------------------------------

# sbt-lint: shared-state
class QualityMonitor:
    """Streaming sketches + drift scores for one serving executor.

    Attach via :func:`attach` (sets ``executor._quality``); the
    executor feeds :meth:`observe_parts` from ``_forward_packed`` —
    the seam under BOTH dispatch paths — and consults
    :meth:`wants_disagreement` once per packed batch. All state sits
    behind one lock; concurrent feeders (the coalescing worker thread
    plus direct-dispatch caller threads) lose no updates.

    ``refresh_every`` rows between drift recomputations + gauge
    exports (1 = every observe — what the deterministic replay gate
    uses). ``disagreement_every`` samples every Nth packed batch
    through the per-replica forward (0 = never). ``min_rows`` is the
    evidence floor: until that many rows are sketched, the exported
    PSI/KS gauges read 0.0 — a ten-row histogram against ten reference
    bins scores PSI ≈ 0.5 of pure sampling noise, and an alert rule
    must not page on it (:meth:`drift` always reports the raw scores
    plus the ``warmed`` flag). ``labels`` scope every exported series
    (:func:`attach` derives ``{"model": <name>}`` for
    registry-registered executors): two monitors writing the SAME
    unlabeled series would clobber each other last-write-wins, and a
    healthy model's refreshes interleaving into the alert window
    would mask a drifting one forever — alert rules must name the
    matching ``labels``.
    """

    def __init__(
        self,
        profile: ReferenceProfile,
        *,
        refresh_every: int = 256,
        disagreement_every: int = 0,
        quantile_rows_per_batch: int = 1,
        export_feature_limit: int = 32,
        min_rows: int = 50,
        labels: dict[str, Any] | None = None,
    ) -> None:
        if refresh_every < 1:
            raise ValueError(
                f"refresh_every must be >= 1, got {refresh_every}"
            )
        if min_rows < 0:
            raise ValueError(f"min_rows must be >= 0, got {min_rows}")
        if disagreement_every < 0:
            raise ValueError(
                f"disagreement_every must be >= 0, got "
                f"{disagreement_every}"
            )
        self.profile = profile
        self.refresh_every = int(refresh_every)
        self.disagreement_every = int(disagreement_every)
        self.quantile_rows_per_batch = max(1, int(quantile_rows_per_batch))
        self.export_feature_limit = int(export_feature_limit)
        self.min_rows = int(min_rows)
        self.labels = dict(labels) if labels else None
        d = profile.n_features
        self._lock = make_lock("telemetry.quality")
        self._edges = [np.asarray(e, np.float64)
                       for e in profile.feature_edges]
        self._feat_counts = np.zeros(
            (d, len(profile.feature_fractions[0])), np.int64
        )
        self._moments = MomentSketch(d)
        self._feat_p50 = [P2Quantile(0.5) for _ in range(d)]
        n_classes = (len(profile.class_fractions)
                     if profile.class_fractions else 0)
        self._class_counts = np.zeros(max(n_classes, 1), np.int64)
        self._conf_counts = np.zeros(CONFIDENCE_BINS, np.int64)
        self._conf_p50 = P2Quantile(0.5)
        self._pred_counts = (
            np.zeros(len(profile.prediction_fractions), np.int64)
            if profile.prediction_fractions else None
        )
        self._pred_edges = (
            np.asarray(profile.prediction_edges, np.float64)
            if profile.prediction_edges else None
        )
        self._rows = 0
        self._since_refresh = 0
        self._batches = 0
        self._dis_sketch = MomentSketch(1)
        self._dis_samples = 0
        self._last_drift: dict[str, Any] | None = None
        self.t_attached = time.time()

    # -- hot-path feeds ------------------------------------------------

    def observe_parts(self, parts, outs) -> None:
        """Feed one packed batch: per-request feature blocks and their
        (already padding-sliced) outputs."""
        for X, out in zip(parts, outs):
            self.observe(X, out)

    def observe(self, X, out=None) -> None:
        """Fold one ``(n, d)`` feature block (and optionally its model
        output) into the sketches. Thread-safe; O(d·bins) per call."""
        X = np.asarray(X)
        n = X.shape[0]
        with self._lock:
            for j, edges in enumerate(self._edges):
                self._feat_counts[j] += bin_counts(X[:, j], edges)
            self._moments.update(X)
            # P² is per-scalar: feed a deterministic row stride so the
            # cost stays O(quantile_rows_per_batch · d) per batch
            step = max(1, n // self.quantile_rows_per_batch)
            for row in X[::step][:self.quantile_rows_per_batch]:
                for j, sk in enumerate(self._feat_p50):
                    sk.update(row[j])
            if out is not None:
                self._observe_output_locked(np.asarray(out))
            self._rows += n
            self._since_refresh += n
            if STATE.enabled:
                STATE.registry.inc("sbt_quality_rows_total", float(n),
                                   self.labels)
            if self._since_refresh >= self.refresh_every:
                self._refresh_locked()

    def _observe_output_locked(self, out: np.ndarray) -> None:
        if self.profile.task == "classification" and out.ndim == 2:
            cls = out.argmax(axis=1)
            counts = np.bincount(cls, minlength=len(self._class_counts))
            # sbt-lint: disable=shared-state-unlocked — the _locked suffix is the contract: every caller holds self._lock (observe())
            self._class_counts += counts[:len(self._class_counts)]
            conf = out.max(axis=1)
            # sbt-lint: disable=shared-state-unlocked — under self._lock (the _locked contract)
            self._conf_counts += bin_counts(
                conf, ReferenceProfile.confidence_edges()
            )
            step = max(1, len(conf) // self.quantile_rows_per_batch)
            for v in conf[::step][:self.quantile_rows_per_batch]:
                self._conf_p50.update(v)
        elif self._pred_counts is not None and out.ndim == 1:
            # sbt-lint: disable=shared-state-unlocked — under self._lock (the _locked contract)
            self._pred_counts += bin_counts(out, self._pred_edges)

    def wants_disagreement(self) -> bool:
        """Once per packed batch: should the executor run the
        per-replica tap for this one? Deterministic counter — the Nth,
        2Nth, ... batches sample."""
        if self.disagreement_every == 0:
            return False
        with self._lock:
            self._batches += 1
            return self._batches % self.disagreement_every == 0

    def observe_disagreement(self, rep_out, task: str) -> dict[str, float]:
        """Fold one per-replica forward's stats in; returns them."""
        stats = disagreement_stats(rep_out, task)
        with self._lock:
            self._dis_sketch.update(
                np.asarray([[stats["disagreement"]]])
            )
            self._dis_samples += 1
        if STATE.enabled:
            STATE.registry.inc("sbt_quality_disagreement_samples_total",
                               1.0, self.labels)
            STATE.registry.observe("sbt_quality_disagreement",
                                   stats["disagreement"], self.labels)
        return stats

    # -- drift math ----------------------------------------------------

    def drift(self) -> dict[str, Any]:
        """Current drift scores (always freshly computed)."""
        with self._lock:
            return self._drift_locked()

    def _drift_locked(self) -> dict[str, Any]:
        prof = self.profile
        feat_psi = [
            psi(prof.feature_fractions[j], self._feat_counts[j])
            for j in range(prof.n_features)
        ]
        feat_ks = [
            ks_stat(prof.feature_fractions[j], self._feat_counts[j])
            for j in range(prof.n_features)
        ]
        out: dict[str, Any] = {
            "rows": self._rows,
            "warmed": self._rows >= self.min_rows,
            "feature_psi": feat_psi,
            "feature_ks": feat_ks,
            "psi_max": max(feat_psi) if feat_psi else 0.0,
            "psi_mean": (sum(feat_psi) / len(feat_psi)
                         if feat_psi else 0.0),
            "ks_max": max(feat_ks) if feat_ks else 0.0,
        }
        if prof.class_fractions is not None:
            out["prediction_psi"] = psi(prof.class_fractions,
                                        self._class_counts)
        if prof.prediction_fractions is not None \
                and self._pred_counts is not None:
            out["prediction_psi"] = psi(prof.prediction_fractions,
                                        self._pred_counts)
        if prof.confidence_fractions is not None:
            out["confidence_psi"] = psi(prof.confidence_fractions,
                                        self._conf_counts)
        conf_p50 = self._conf_p50.value()
        if math.isfinite(conf_p50):
            out["confidence_p50"] = conf_p50
        if self._dis_samples:
            out["disagreement_mean"] = float(
                self._dis_sketch.mean()[0]
            )
            out["disagreement_samples"] = self._dis_samples
        return out

    def refresh(self) -> dict[str, Any]:
        """Recompute drift and export the gauges now (also runs
        automatically every ``refresh_every`` observed rows)."""
        with self._lock:
            return self._refresh_locked()

    def _refresh_locked(self) -> dict[str, Any]:
        # sbt-lint: disable=shared-state-unlocked — the _locked suffix is the contract: every caller holds self._lock
        self._since_refresh = 0
        drift = self._drift_locked()
        # sbt-lint: disable=shared-state-unlocked — under self._lock (the _locked contract)
        self._last_drift = drift
        if STATE.enabled:
            # lock order: quality -> registry (the exporter direction;
            # the registry never calls back into quality)
            reg = STATE.registry

            def gated(v: float) -> float:
                # below the evidence floor the gauges read 0.0 — the
                # alert plane must not see small-sample noise as drift
                return v if drift["warmed"] else 0.0

            lbl = self.labels
            reg.set("sbt_quality_psi_max", gated(drift["psi_max"]), lbl)
            reg.set("sbt_quality_psi_mean", gated(drift["psi_mean"]),
                    lbl)
            reg.set("sbt_quality_ks_max", gated(drift["ks_max"]), lbl)
            # signals this monitor cannot produce (no confidence
            # reference, no disagreement sampling) export 0.0 — "no
            # evidence of drift" — rather than being skipped: a skip
            # would FREEZE the previous monitor's value in the gauge,
            # and a re-attached model without that signal would keep a
            # stale breaching value alive under the alert rules
            reg.set("sbt_quality_prediction_psi",
                    gated(drift.get("prediction_psi", 0.0)), lbl)
            reg.set("sbt_quality_confidence_psi",
                    gated(drift.get("confidence_psi", 0.0)), lbl)
            reg.set("sbt_quality_confidence_p50",
                    drift.get("confidence_p50", 0.0), lbl)
            reg.set("sbt_quality_disagreement_mean",
                    drift.get("disagreement_mean", 0.0), lbl)
            # per-feature series are CAPPED, not all-or-nothing: the
            # first export_feature_limit features export (bounding
            # scrape cardinality for wide models), the rest stay
            # aggregate-only — summary() reports the split
            n_export = min(self.profile.n_features,
                           self.export_feature_limit)
            for j in range(n_export):
                labels = {**(lbl or {}), "feature": str(j)}
                reg.set("sbt_quality_feature_psi",
                        gated(drift["feature_psi"][j]), labels)
                reg.set("sbt_quality_feature_ks",
                        gated(drift["feature_ks"][j]), labels)
            reg.inc("sbt_quality_refresh_total", 1.0, lbl)
        return drift

    # -- introspection -------------------------------------------------

    def summary(self) -> dict[str, Any]:
        """JSON digest for ``/debug/drift``."""
        with self._lock:
            last = self._last_drift
            feat_p50 = [sk.value() for sk in self._feat_p50]
            return {
                "labels": self.labels,
                "task": self.profile.task,
                "n_features": self.profile.n_features,
                "reference_rows": self.profile.n_rows,
                "confidence_source": self.profile.confidence_source,
                "rows_observed": self._rows,
                "batches": self._batches,
                "feature_series_exported": min(
                    self.profile.n_features, self.export_feature_limit
                ),
                "refresh_every": self.refresh_every,
                "disagreement_every": self.disagreement_every,
                "disagreement_samples": self._dis_samples,
                "feature_p50": [
                    v if math.isfinite(v) else None for v in feat_p50
                ],
                "feature_mean": [
                    v if math.isfinite(v) else None
                    for v in self._moments.mean().tolist()
                ],
                "drift": last,
                "t_attached": self.t_attached,
            }


# -- process-level attach registry --------------------------------------

_monitors_lock = make_lock("telemetry.quality.monitors")
_monitors: list[Any] = []  # weakrefs, pruned on read and insert


def attach(executor, *, profile=None, monitor: QualityMonitor | None = None,
           **monitor_opts: Any) -> QualityMonitor:
    """Attach a drift monitor to a serving executor's hot path.

    ``profile`` defaults to the executor's model's ``quality_profile_``
    (what ``fit()`` computes and checkpoints round-trip); pass a
    :class:`ReferenceProfile` (or its dict form) to override, or a
    ready ``monitor`` to install directly. Gauge ``labels`` default to
    ``{"model": executor.model_name}`` for registry-registered
    executors (anonymous executors export unlabeled) so two monitored
    models never clobber each other's series — point alert rules at
    the matching labels. The returned monitor is registered for
    ``/debug/drift`` (weakly — it dies with its executor) and exports
    its initial gauges immediately, so stale values from a previous
    monitor never leak into fresh rules.
    """
    if monitor is None:
        if "labels" not in monitor_opts:
            name = getattr(executor, "model_name", None)
            if name is not None:
                monitor_opts["labels"] = {"model": str(name)}
        if profile is None:
            profile = getattr(
                getattr(executor, "model", None), "quality_profile_", None
            )
            if profile is None:
                raise ValueError(
                    "executor's model carries no quality_profile_ "
                    "(fitted by an older build, or a stream fit); pass "
                    "profile= explicitly or rebuild with "
                    "ReferenceProfile.from_training"
                )
        if isinstance(profile, dict):
            profile = ReferenceProfile.from_dict(profile)
        monitor = QualityMonitor(profile, **monitor_opts)
    executor.attach_quality(monitor)
    if monitor.disagreement_every and hasattr(executor,
                                              "warmup_replica"):
        # pre-build the per-replica executables for every bucket the
        # serving forward already compiled: the sampled batches must
        # never absorb an XLA compile stall on the live serving
        # thread (later-compiled buckets still build lazily)
        executor.warmup_replica()
    with _monitors_lock:
        _monitors[:] = [r for r in _monitors if r() is not None]
        _monitors.append(weakref.ref(monitor))
    monitor.refresh()
    return monitor


def monitors() -> list[QualityMonitor]:
    """Live attached monitors (dead ones pruned)."""
    with _monitors_lock:
        out = [r() for r in _monitors]
        _monitors[:] = [r for r, m in zip(_monitors, out)
                        if m is not None]
    return [m for m in out if m is not None]


def debug_summary() -> dict[str, Any]:
    """What ``/debug/drift`` serves."""
    live = monitors()
    if not live:
        return {
            "monitors": [],
            "note": "no quality monitor attached; use "
                    "telemetry.quality.attach(executor) or "
                    "ModelRegistry.enable_quality(name)",
        }
    return {"monitors": [m.summary() for m in live]}
