"""Nestable phase spans — the host-side half of the tracing story.

``span("bootstrap")`` records wall-clock for a phase into the current
run's event stream, maintains a per-thread nesting stack (so events
carry a full ``path`` like ``fit/compile``), and composes with
``jax.named_scope``: a span opened inside a jit trace enters the same
name as a scope, so host spans and device traces (TensorBoard/Perfetto
via ``utils/profiling.trace``) segment by the SAME phase names — the
Spark-UI-stages analog [SURVEY §5]. When a request trace context is
installed on the thread (``telemetry.tracing``), every span event
additionally carries ``trace_id``/``span_id``/``parent_id`` (and, for
batch-level contexts, ``links`` to member request traces), turning the
event stream into a queryable per-request span tree.

Two cost tiers, per the zero-overhead-when-disabled contract:

- disabled: ``span()`` returns a shared no-op context manager (or a
  bare ``jax.named_scope`` from ``phase()``, preserving the device
  trace annotation the engines always had) — no clock reads, no
  allocation.
- enabled: two ``perf_counter`` reads plus an event append; optional
  **device-sync** timing (``set_device_sync(True)``) drains the
  dispatch queue at span exit so the wall-clock covers the device work
  launched inside the span, not just its dispatch — opt-in because the
  barrier serializes the pipeline it is measuring.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Iterator

from spark_bagging_tpu.telemetry import tracing
from spark_bagging_tpu.telemetry.state import STATE as _state


class _Nesting(threading.local):
    def __init__(self) -> None:
        self.stack: list[str] = []


_nesting = _Nesting()


def _device_barrier() -> None:
    """Best-effort full-queue drain: enqueue a trivial computation and
    block on it (per-device streams execute in order, so its completion
    bounds all previously dispatched work)."""
    import jax
    import jax.numpy as jnp

    from spark_bagging_tpu.analysis import locks

    # a sync span entered while holding an instrumented lock would park
    # every waiter behind the device queue — record the hazard when
    # lock debugging is on (free otherwise: one module-flag read)
    locks.note_device_sync("telemetry span device barrier")
    jax.block_until_ready(jnp.zeros(()))


class _NoopSpan:
    """Shared do-nothing context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NOOP = _NoopSpan()


@contextmanager
def _record_span(
    name: str, attrs: dict[str, Any] | None, metric: str | None,
    sync: bool | None,
) -> Iterator[None]:
    stack = _nesting.stack
    do_sync = _state.device_sync if sync is None else sync
    if do_sync:
        # entry barrier BEFORE the stack push: if the device is already
        # wedged this raises without corrupting the nesting state
        _device_barrier()
    stack.append(name)
    path = "/".join(stack)
    tctx = tracing.current()
    trace_fields = tctx.begin_span() if tctx is not None else None
    t0 = time.perf_counter()
    t0_epoch = time.time()
    try:
        yield
    finally:
        # pop FIRST — later spans on this thread must not inherit a
        # stale path prefix no matter what the barrier below does
        stack.pop()
        if tctx is not None:
            tctx.end_span()
        if do_sync:
            try:
                _device_barrier()
            # sbt-lint: disable=swallowed-fault — deliberate: the body's own exception (already propagating) must not be masked by the measurement barrier failing for the same cause
            except Exception:  # noqa: BLE001 — a body exception (the
                # device failing mid-span) must not be masked by the
                # measurement barrier failing for the same reason
                pass
        dt = time.perf_counter() - t0
        if metric is not None:
            _state.registry.observe(metric, dt)
        event = {
            "kind": "span",
            "name": name,
            "path": path,
            "ts": t0_epoch,
            "seconds": dt,
            "sync": bool(do_sync),
        }
        if trace_fields is not None:
            event.update(trace_fields)
        if attrs:
            event["attrs"] = {k: _jsonable(v) for k, v in attrs.items()}
        _state.emit(event)


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def span(
    name: str,
    *,
    metric: str | None = None,
    sync: bool | None = None,
    **attrs: Any,
):
    """Record a nestable host phase span named ``name``.

    ``metric`` additionally folds the duration into that log-scale
    histogram in the registry (e.g. per-chunk latencies). ``sync``
    forces device-sync timing on/off for this span regardless of the
    global opt-in. No-op (one attribute read) when telemetry is
    disabled.
    """
    if not _state.enabled:
        return _NOOP
    return _record_span(name, attrs or None, metric, sync)


def phase(name: str, *, sync: bool | None = None, **attrs: Any):
    """``span()`` fused with ``jax.named_scope``: the engine phases
    (prepare/bootstrap/base_fit/aggregate) annotate the device trace
    under the same name the host span records, so the two timelines
    correlate by name. When telemetry is disabled this degrades to the
    bare ``named_scope`` the engines always used — identical device
    traces, zero added host work. Inside a jit trace the host span
    measures trace-construction time (recorded with ``traced=True``);
    outside it measures the real phase.
    """
    import jax

    scope = jax.named_scope(name)
    if not _state.enabled:
        return scope
    traced = _under_trace()
    if traced:
        attrs = dict(attrs, traced=True)
        sync = False  # tracing is host work; a barrier adds nothing
    return _Both(scope, _record_span(name, attrs or None, None, sync))


def _under_trace() -> bool:
    """Are we inside jax tracing (jit/vmap/scan body) right now?"""
    import jax

    try:
        return not jax.core.trace_state_clean()
    # sbt-lint: disable=swallowed-fault — version-probe fallback (jax vintages without trace_state_clean); "not tracing" is the safe answer, and telemetry must never break a trace
    except Exception:  # noqa: BLE001 — never let telemetry break a trace
        return False


class _Both:
    """Enter/exit two context managers as one (scope outer, span inner)."""

    __slots__ = ("_a", "_b")

    def __init__(self, a, b) -> None:
        self._a, self._b = a, b

    def __enter__(self):
        self._a.__enter__()
        try:
            self._b.__enter__()
        except BaseException:
            self._a.__exit__(None, None, None)
            raise
        return self

    def __exit__(self, *exc):
        try:
            self._b.__exit__(*exc)
        finally:
            self._a.__exit__(*exc)
        return None
