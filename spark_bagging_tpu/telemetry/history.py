"""Longitudinal verification history — the trend store under the
verification observatory [ROADMAP item 5].

Every verification run that produces a deterministic identity — a
scenario-conformance pass (``benchmarks/scenarios``), a serving bench
(``benchmarks/serving_latency.py``), a full tier-1 session (the
``test_zz_tier_budget`` ratchet) — appends ONE compact record to
``telemetry_dir()/history/history.jsonl``: run id, the digests that
prove determinism, SLO outcomes, and the headline numbers worth
trending (tier wall-clock per module, bench rps ratios). The file is
append-only JSONL so concurrent writers interleave whole lines and a
torn tail line degrades to a skipped record, never a broken store.

:func:`compare_trend` is the read half: it groups records by
``(kind, key)`` and separates the two failure classes regression
tracking must never conflate —

- **digest flips** (a deterministic identity changed between runs):
  exact, no tolerance, always a finding. Same for an SLO verdict going
  ``ok -> failed``.
- **numeric drift** (wall-clock, rps): judged against a CI-noise band
  (default ``NOISE_TOLERANCE``, the replay gate's rps band) around the
  median of the PRIOR runs in the group — run-to-run wobble inside the
  band is reported as stable, movement beyond it as drift. Advisory:
  drift warns, only flips fail (``ok`` is "no flips").

Surfaced via ``python -m benchmarks.scenarios history`` and the scrape
server's ``/debug/history`` route. History lives under the telemetry
dir on purpose: run artifacts, not source (the ``/telemetry/``
gitignore rule covers it); the committed regression surface is the
scenario baseline set under ``benchmarks/baselines/scenarios/``.
"""

from __future__ import annotations

import json
import os
import statistics
import time
from typing import Any

from spark_bagging_tpu.telemetry.sinks import telemetry_dir

HISTORY_SCHEMA_VERSION = 1

#: the CI-noise band for numeric trend fields — deliberately the same
#: width as the replay gate's rps tolerance (telemetry/slo.py): both
#: hunt decisive movement, not scheduler wobble on a shared host
NOISE_TOLERANCE = 0.35

#: record kinds the store knows about (anything else is accepted —
#: the schema is open — but these are what the repo's writers append)
KNOWN_KINDS = ("scenario", "bench", "tier")


def history_dir() -> str:
    """``telemetry_dir()/history`` — created on first use, covered by
    the existing ``/telemetry/`` gitignore rule like every other run
    artifact."""
    path = os.path.join(telemetry_dir(), "history")
    os.makedirs(path, exist_ok=True)
    return path


def history_path() -> str:
    return os.path.join(history_dir(), "history.jsonl")


def append_record(
    kind: str,
    key: str,
    *,
    digests: dict[str, str] | None = None,
    numbers: dict[str, float] | None = None,
    slo_ok: bool | None = None,
    detail: dict[str, Any] | None = None,
    run_id: str | None = None,
    ts: float | None = None,
    path: str | None = None,
) -> dict[str, Any]:
    """Append one compact record; returns what was written.

    ``digests`` are the exact-identity fields :func:`compare_trend`
    treats as flips when they change; ``numbers`` are trended against
    the noise band; ``detail`` rides along unjudged (per-module tier
    seconds, bench sub-reports). ``ts``/``run_id`` are injectable so
    replay-driven writers stay deterministic.
    """
    from spark_bagging_tpu import telemetry

    ts = time.time() if ts is None else float(ts)
    record = {
        "schema": HISTORY_SCHEMA_VERSION,
        "ts": ts,
        "run_id": run_id or f"{kind}-{key}-{int(ts * 1e3)}-{os.getpid()}",
        "kind": kind,
        "key": key,
    }
    if digests:
        record["digests"] = dict(digests)
    if numbers:
        record["numbers"] = {k: float(v) for k, v in numbers.items()}
    if slo_ok is not None:
        record["slo_ok"] = bool(slo_ok)
    if detail:
        record["detail"] = detail
    out = path or history_path()
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "a+b") as f:
        # a writer killed mid-append leaves a torn tail with no
        # newline; gluing the next record onto it would corrupt BOTH.
        # One seek+read per append keeps every later record intact
        # (the torn fragment itself degrades to one skipped line).
        f.seek(0, os.SEEK_END)
        if f.tell() > 0:
            f.seek(-1, os.SEEK_END)
            if f.read(1) != b"\n":
                f.write(b"\n")
        f.write(json.dumps(record, sort_keys=True).encode() + b"\n")
    telemetry.inc("sbt_history_appends_total")
    return record


def read_history(path: str | None = None,
                 limit: int | None = None) -> list[dict[str, Any]]:
    """Read the store in append order. A torn or garbage line (a
    writer killed mid-append) is skipped, never fatal — the store is
    observability, and one lost record beats a broken trend page.
    ``limit`` keeps the NEWEST records."""
    src = path or history_path()
    records: list[dict[str, Any]] = []
    if not os.path.exists(src):
        return records
    with open(src) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    if limit is not None and limit >= 0:
        # limit=0 means NONE: records[-0:] would slice from the start
        # and return everything (the /debug/spans lesson)
        records = records[-limit:] if limit > 0 else []
    return records


def _group_key(rec: dict[str, Any]) -> str:
    return f"{rec.get('kind', '?')}:{rec.get('key', '?')}"


def compare_trend(
    records: list[dict[str, Any]],
    *,
    tolerance: float = NOISE_TOLERANCE,
) -> dict[str, Any]:
    """The longitudinal verdict over a record list (typically
    :func:`read_history`'s output).

    Per ``(kind, key)`` group, in record order:

    - every ``digests`` entry that CHANGES between consecutive runs is
      a **flip** (exact comparison — determinism has no noise band);
      an ``slo_ok`` transition ``true -> false`` is flagged the same
      way (class ``slo``);
    - the newest ``numbers`` entry is compared against the median of
      the group's PRIOR values: relative movement beyond ``tolerance``
      is **drift**, inside it stable. Needs >= 2 runs; a single run
      has no trend.

    Returns ``{"groups": {...}, "flips": [...], "drift": [...],
    "runs": N, "ok": bool}`` — ``ok`` is "no flips" (drift is
    advisory; the absolute gates live in the scenario SLOs).
    """
    groups: dict[str, list[dict[str, Any]]] = {}
    for rec in records:
        groups.setdefault(_group_key(rec), []).append(rec)

    flips: list[dict[str, Any]] = []
    drift: list[dict[str, Any]] = []
    group_out: dict[str, Any] = {}
    for gkey, recs in groups.items():
        g_flips: list[dict[str, Any]] = []
        # flips compare against the LAST-KNOWN value per field, not
        # the immediately preceding record: a run that carries no
        # slo_ok (a `record`/`run` append) or omits a digest field
        # interleaved between two checks must not mask a regression
        last_digest: dict[str, tuple[str, Any]] = {}
        last_slo: tuple[str, Any] | None = None
        for cur in recs:
            for name, value in sorted(
                    (cur.get("digests") or {}).items()):
                known = last_digest.get(name)
                if known is not None and known[1] != value:
                    g_flips.append({
                        "group": gkey, "class": "digest",
                        "field": name,
                        "from": known[1], "to": value,
                        "run_from": known[0],
                        "run_to": cur.get("run_id"),
                        "ts": cur.get("ts"),
                    })
                last_digest[name] = (cur.get("run_id"), value)
            slo_ok = cur.get("slo_ok")
            if slo_ok is not None:
                if last_slo is not None and last_slo[1] is True \
                        and slo_ok is False:
                    g_flips.append({
                        "group": gkey, "class": "slo",
                        "field": "slo_ok",
                        "from": True, "to": False,
                        "run_from": last_slo[0],
                        "run_to": cur.get("run_id"),
                        "ts": cur.get("ts"),
                    })
                last_slo = (cur.get("run_id"), slo_ok)
        g_drift: list[dict[str, Any]] = []
        if len(recs) >= 2:
            latest = recs[-1].get("numbers") or {}
            for name in sorted(latest):
                prior = [r["numbers"][name] for r in recs[:-1]
                         if name in (r.get("numbers") or {})]
                if not prior:
                    continue
                ref = statistics.median(prior)
                if ref == 0:
                    continue
                rel = (latest[name] - ref) / abs(ref)
                if abs(rel) > tolerance:
                    g_drift.append({
                        "group": gkey, "field": name,
                        "baseline_median": round(ref, 6),
                        "latest": round(float(latest[name]), 6),
                        "relative": round(rel, 4),
                        "tolerance": tolerance,
                        "run": recs[-1].get("run_id"),
                    })
        flips += g_flips
        drift += g_drift
        group_out[gkey] = {
            "runs": len(recs),
            "first_ts": recs[0].get("ts"),
            "last_ts": recs[-1].get("ts"),
            "last_run_id": recs[-1].get("run_id"),
            "flips": len(g_flips),
            "drift": len(g_drift),
        }

    out = {
        "runs": len(records),
        "groups": group_out,
        "flips": flips,
        "drift": drift,
        "ok": not flips,
    }
    _export_gauges(out)
    return out


def _export_gauges(trend: dict[str, Any]) -> None:
    """Mirror the latest trend scan as ``sbt_history_*`` gauges so a
    scrape-only deployment sees the verdict without reading JSONL.
    Gauges, not counters: a scrape loop re-running the scan must not
    inflate a total."""
    from spark_bagging_tpu import telemetry

    telemetry.set_gauge("sbt_history_records", float(trend["runs"]))
    telemetry.set_gauge("sbt_history_groups",
                        float(len(trend["groups"])))
    telemetry.set_gauge("sbt_history_digest_flips",
                        float(len(trend["flips"])))
    telemetry.set_gauge("sbt_history_numeric_drift",
                        float(len(trend["drift"])))


def history_report(limit: int = 32,
                   path: str | None = None) -> dict[str, Any]:
    """The ``/debug/history`` route body (also the CLI's source): the
    newest ``limit`` records plus the trend verdict over the FULL
    store (trend over a truncated window would miss older flips)."""
    records = read_history(path)
    trend = compare_trend(records)
    limit = max(0, int(limit))
    return {
        "path": path or history_path(),
        "runs": len(records),
        "records": records[-limit:] if limit > 0 else [],
        "trend": trend,
    }


def render_history(report: dict[str, Any]) -> str:
    """Human one-screen rendering for the CLI: per-group run counts
    and verdicts, then any flips/drift in full."""
    lines = [f"history: {report['path']} ({report['runs']} runs)"]
    trend = report["trend"]
    for gkey in sorted(trend["groups"]):
        g = trend["groups"][gkey]
        verdict = "FLIP" if g["flips"] else (
            "drift" if g["drift"] else "stable")
        lines.append(
            f"  [{verdict:>6}] {gkey}: {g['runs']} runs "
            f"(last {g['last_run_id']})"
        )
    for f in trend["flips"]:
        lines.append(
            f"  FLIP {f['group']} {f['field']}: "
            f"{str(f['from'])[:16]} -> {str(f['to'])[:16]} "
            f"({f['run_from']} -> {f['run_to']})"
        )
    for d in trend["drift"]:
        lines.append(
            f"  drift {d['group']} {d['field']}: "
            f"{d['baseline_median']} -> {d['latest']} "
            f"({d['relative']:+.0%} vs ±{d['tolerance']:.0%} band)"
        )
    lines.append("trend OK" if trend["ok"]
                 else "trend DIGEST FLIP detected")
    return "\n".join(lines)
