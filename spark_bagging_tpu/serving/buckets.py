"""Shape-bucket math: power-of-two row buckets for zero-recompile serving.

XLA compiles one executable per input SHAPE. Online traffic brings a
new row count on nearly every request, so feeding requests straight to
a jitted forward would recompile constantly — the exact failure mode
the serving subsystem exists to remove. The fix is the standard one:
quantize row counts to a small ladder of power-of-two buckets, pad each
batch up to its bucket, and slice the padding back off the output. The
ladder between ``min_rows`` and ``max_rows`` has ``log2(max/min) + 1``
rungs, so steady-state traffic touches a FINITE set of shapes: after
one warmup pass over the ladder, no request can ever trigger another
compile (asserted in tests/test_serving.py via the
``sbt_serving_compiles_total`` counter).

Padding rows are zeros. They flow through the ensemble forward like any
other row and produce garbage outputs — which is fine, because bagging
aggregation is strictly row-local (vote/mean over replicas, per row):
a padded row can never contaminate a real row's result. The executor
slices ``[:n]`` before anything user-visible happens; the
padding-never-leaks property is tested bitwise.
"""

from __future__ import annotations

import numpy as np

#: Default bucket ladder bounds — 8..4096 rows covers single-row
#: requests (padded 8x at worst, still one tile) up to the largest
#: micro-batch the default batcher will coalesce.
DEFAULT_MIN_ROWS = 8
DEFAULT_MAX_ROWS = 4096


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    return 1 << (int(n) - 1).bit_length()


def bucket_for(n: int, min_rows: int = DEFAULT_MIN_ROWS,
               max_rows: int = DEFAULT_MAX_ROWS) -> int:
    """The bucket (padded row count) a batch of ``n`` rows runs in.

    Bounds are normalized to powers of two first (exactly as
    :func:`bucket_ladder` normalizes them), so every value this can
    return is a ladder rung — the zero-recompile-after-warmup contract
    must hold for ANY bounds, not just power-of-two ones. ``n`` above
    ``max_rows`` still maps to the top rung — the executor splits
    oversized batches into top-bucket slabs first, so the
    compiled-shape set stays bounded by the ladder no matter what a
    caller submits.
    """
    if n < 1:
        raise ValueError(f"batch must have >= 1 row, got {n}")
    return max(next_pow2(min_rows), min(next_pow2(n), next_pow2(max_rows)))


def bucket_ladder(min_rows: int = DEFAULT_MIN_ROWS,
                  max_rows: int = DEFAULT_MAX_ROWS) -> tuple[int, ...]:
    """Every bucket between the bounds — the warmup compile set."""
    if not (1 <= min_rows <= max_rows):
        raise ValueError(
            f"need 1 <= min_rows <= max_rows, got {min_rows}, {max_rows}"
        )
    lo, hi = next_pow2(min_rows), next_pow2(max_rows)
    out = []
    b = lo
    while b <= hi:
        out.append(b)
        b *= 2
    return tuple(out)


def pack_plan(n: int, min_rows: int = DEFAULT_MIN_ROWS,
              max_rows: int = DEFAULT_MAX_ROWS) -> tuple[int, ...]:
    """Slab buckets for serving ``n`` rows with the least padded work.

    A single bucket wastes up to half its rows (``n`` just past a rung
    pads nearly 2x): 20 rows in bucket 32 burns 12 padding rows — 37%
    of the forward's FLOPs. Decomposing the batch into a descending
    run of FULL smaller rungs instead (``20 -> 16 + 8``, only the last
    slab padded) never pads more rows than the single bucket and often
    pads far fewer, at the cost of one extra executable launch per
    extra slab. This returns that plan:

    - row counts above the top rung emit full top-rung slabs first
      (the existing oversize-slab rule, unchanged);
    - the residual is decomposed greedily into full rungs, adjacent
      equal rungs are re-merged (two half slabs over the same rows ARE
      the double slab — same padding, one fewer launch), and the
      decomposition is kept only when it saves at least a QUARTER of
      the single bucket's rows: an extra executable launch has a real
      fixed cost, and shaving a couple of padding rows does not buy it
      back (the single bucket wins all ties and near-ties);
    - every element is a ladder rung, so the compile-shape universe is
      still exactly :func:`bucket_ladder` — zero-recompile-after-warmup
      survives ragged packing.

    Fill rule for consumers: slabs are ordered so only the LAST one is
    partial — walk the plan assigning ``min(bucket, remaining)`` rows
    to each slab.
    """
    if n < 1:
        raise ValueError(f"batch must have >= 1 row, got {n}")
    lo, hi = next_pow2(min_rows), next_pow2(max_rows)
    if lo > hi:
        raise ValueError(
            f"need min_rows <= max_rows, got {min_rows}, {max_rows}"
        )
    plan: list[int] = []
    while n > hi:
        plan.append(hi)
        n -= hi
    # residual in [1, hi]: greedy binary decomposition into full rungs
    greedy: list[int] = []
    r = n
    while r:
        b = max(lo, next_pow2(r))
        if r == b or b // 2 < lo:
            greedy.append(b)  # exact fit, or the floor rung (padded)
            r = 0
        else:
            greedy.append(b // 2)  # full slab; recurse on the rest
            r -= b // 2
    # re-merge equal tail rungs ([.., 8, 8] -> [.., 16], cascading):
    # identical row coverage, strictly fewer launches
    while len(greedy) >= 2 and greedy[-1] == greedy[-2]:
        greedy[-2:] = [greedy[-1] * 2]
    single = max(lo, next_pow2(n))
    saved = single - sum(greedy)
    if len(greedy) > 1 and saved * 4 >= single:
        plan.extend(greedy)
    else:
        plan.append(single)
    return tuple(plan)


def pad_to_bucket(X: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad ``X``'s rows up to ``bucket`` (host-side; the padded
    block is the h2d transfer unit)."""
    n = X.shape[0]
    if n > bucket:
        raise ValueError(f"{n} rows do not fit bucket {bucket}")
    if n == bucket:
        return X
    Xp = np.zeros((bucket,) + X.shape[1:], X.dtype)
    Xp[:n] = X
    return Xp
