"""AOT executable persistence — instant-warm serving starts.

An :class:`~spark_bagging_tpu.serving.executor.EnsembleExecutor`
reaches its zero-recompile steady state only after every ladder rung
has been lowered and compiled — seconds to minutes of warmup a freshly
started serving process pays while traffic waits (or a load balancer
holds it out of rotation). XLA's compiled executables are serializable
(``jax.experimental.serialize_executable``), so the warmup is a
write-once artifact: this module persists each bucket's executable
next to the model checkpoint and hydrates a fresh executor from it —
no tracing, no lowering, no compile, zero entries added to
``sbt_serving_compiles_total``.

Cache-key contract: a persisted executable is only valid for exactly
the program it was compiled from, on the toolchain that compiled it.
The manifest records — and :func:`restore_executables` requires equal —

- ``model_fingerprint``: sha256 over the fitted params pytree (leaf
  bytes + shapes + dtypes + treedef), the subspace matrix, estimator
  class, task, feature width, and class set — two models that would
  compile different programs fingerprint differently (shared with the
  in-process unified cache: ``program_cache.fingerprint_params``);
- ``ladder``: the executor's ``(min_bucket_rows, max_batch_rows)``
  bounds — the compile-shape universe;
- ``mesh``: the serving mesh's ``(data, replica)`` shape, or None for
  a single-device executor — a single-device executable restored into
  a mesh-sharded executor (or vice versa) would be the WRONG program:
  the mismatch is a counted miss and the executor lowers its own,
  never a crash and never a silently single-device serving path;
- ``jax_version`` / ``backend`` / ``n_devices`` / ``device_kind`` —
  XLA serialization is only stable within one toolchain + hardware
  shape + chip generation;
- ``donate``: donation changes the compiled program's aliasing.

Any mismatch (or an absent/corrupt cache) is a MISS, never an error:
the executor falls back to lowering exactly as if no cache existed,
counting ``sbt_serving_aot_misses_total``. Like model checkpoints, the
cache directory is TRUSTED input — payloads are unpickled (the same
trust stance as ``utils/checkpoint._import_class``), so only load
caches you produced.

Layout (``<dir>/``)::

    aot_manifest.json     # {"key": {...}, "buckets": {"8": "bucket_8.bin", ...}}
    bucket_<b>.bin        # pickled (payload, in_tree, out_tree) triple

``ModelRegistry.save()`` writes this directory as ``serving_aot/``
inside the checkpoint dir; ``ModelRegistry.load()`` auto-detects it.
"""

from __future__ import annotations

import json
import os
import pickle
import warnings
from typing import Any

from spark_bagging_tpu import telemetry

FORMAT_VERSION = 1
MANIFEST = "aot_manifest.json"


def _count(executor: Any, series: str) -> None:
    """Count one AOT event, unlabeled always and ``model=``-labeled
    when the executor is registry-committed [ISSUE 16]. Restores that
    run during ``register``/``swap`` pre-commit happen BEFORE the name
    is stamped and stay unlabeled — deliberately: labels exist only
    for owners a commit established, matching the capacity plane's
    attribution contract."""
    telemetry.inc(series)
    name = getattr(executor, "model_name", None)
    if name is not None:
        telemetry.inc(series, labels={"model": str(name)})


def dir_nbytes(path: str) -> int:
    """Total bytes on disk under an AOT cache directory."""
    total = 0
    try:
        with os.scandir(path) as it:
            for entry in it:
                if entry.is_file():
                    total += entry.stat().st_size
    except OSError:
        return 0
    return total


def model_fingerprint(executor: Any) -> str:
    """sha256 identity of the program an executor compiles — the SAME
    fingerprint the in-process unified cache keys on
    (``program_cache.fingerprint_params``), so the disk cache and the
    process cache agree on what "the same model" means. Executors
    compute it once at construction; anything else falls back to
    hashing here."""
    fp = getattr(executor, "fingerprint", None)
    if fp is not None:
        return fp
    from spark_bagging_tpu.serving.program_cache import fingerprint_params

    return fingerprint_params(
        type(executor.model), executor.task, executor.n_features,
        executor.classes_, executor._params, executor._subspaces,
    )


def cache_key(executor: Any) -> dict[str, Any]:
    """The validity contract a restore checks for equality — see the
    module docstring."""
    import jax

    mesh_shape = getattr(executor, "mesh_shape", None)
    failed = sorted(getattr(executor, "_failed_shards", ()) or ())
    devices = jax.devices()
    return {
        "format": FORMAT_VERSION,
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "n_devices": jax.device_count(),
        "device_kind": str(devices[0].device_kind) if devices else "unknown",
        "mesh": list(mesh_shape) if mesh_shape is not None else None,
        # a degraded (surviving-subset) executor's programs are the
        # WRONG program for a healthy executor and vice versa — the
        # failed-shard set is part of the program identity
        "degraded": [int(s) for s in failed] or None,
        "ladder": [int(executor.min_bucket_rows),
                   int(executor.max_batch_rows)],
        "donate": bool(executor._donate),
        "model_fingerprint": model_fingerprint(executor),
    }


def covers(executor: Any, path: str) -> bool:
    """True iff the cache at ``path`` was written for exactly this
    executor's program key and already holds every bucket the executor
    currently has compiled — the test a residency demotion uses to
    SKIP re-saving. The skip is load-bearing, not an optimisation:
    re-serializing an executable that was itself deserialized is not
    round-trip-stable on every backend (XLA:CPU loses kernel symbols),
    so a demote→restore→demote cycle that re-saved would clobber a
    good cache with unloadable payloads."""
    manifest_path = os.path.join(path, MANIFEST)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return False
    if manifest.get("key") != cache_key(executor):
        return False
    entries = manifest.get("buckets")
    if not isinstance(entries, dict):
        return False
    try:
        saved = {int(b) for b in entries}
    except (TypeError, ValueError):
        return False
    return set(executor.compiled_buckets) <= saved


def save_executables(executor: Any, path: str) -> tuple[int, ...]:
    """Persist every bucket executable ``executor`` has compiled into
    directory ``path`` (atomic install: built in a tmp dir, then
    swapped in). Buckets whose executable the backend cannot serialize
    are skipped with a warning. Returns the buckets saved."""
    from jax.experimental import serialize_executable

    with executor._build_lock:
        compiled = dict(executor._compiled)
    if not compiled:
        raise ValueError(
            "executor has no compiled buckets to persist; run "
            "warmup() (or serve traffic) before save_executables()"
        )
    import shutil

    tmp = f"{path}.tmp.{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    saved: dict[str, str] = {}
    for bucket in sorted(compiled):
        try:
            triple = serialize_executable.serialize(compiled[bucket])
        except Exception as e:  # noqa: BLE001 — backend-dependent support
            warnings.warn(
                f"bucket {bucket} executable is not serializable on "
                f"this backend ({e!r}); a warm start will lower it "
                "instead",
                stacklevel=2,
            )
            continue
        fname = f"bucket_{bucket}.bin"
        with open(os.path.join(tmp, fname), "wb") as f:
            pickle.dump(triple, f)
        saved[str(bucket)] = fname
        _count(executor, "sbt_serving_aot_saved_total")
    with open(os.path.join(tmp, MANIFEST), "w") as f:
        json.dump({"key": cache_key(executor), "buckets": saved}, f,
                  indent=2)
    from spark_bagging_tpu import faults

    if faults.ACTIVE is not None:
        # torn-write drill: a kill HERE leaves only the tmp dir — no
        # cache is installed, a later restore is a counted miss
        faults.fire("aot.save")
    if os.path.exists(path):
        shutil.rmtree(path)
    os.replace(tmp, path)
    # capacity ledger feed [ISSUE 16]: disk bytes this model's AOT
    # cache now holds, attributed only when the executor is committed
    name = getattr(executor, "model_name", None)
    if name is not None:
        from spark_bagging_tpu.telemetry import capacity as _capacity

        cap = _capacity.ACTIVE
        if cap is not None:
            cap.set_aot_bytes(str(name),
                              int(executor.model_version or 0),
                              dir_nbytes(path))
    return tuple(int(b) for b in sorted(saved, key=int))


def restore_executables(executor: Any, path: str) -> tuple[int, ...]:
    """Hydrate ``executor`` from a cache written by
    :func:`save_executables`. Every failure mode is a MISS (counted,
    warned where surprising, never raised): the executor simply lowers
    on demand as if no cache existed. Returns the buckets restored."""
    from jax.experimental import serialize_executable

    manifest_path = os.path.join(path, MANIFEST)
    if not os.path.isfile(manifest_path):
        _count(executor, "sbt_serving_aot_misses_total")
        return ()
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        _count(executor, "sbt_serving_aot_misses_total")
        _count(executor, "sbt_aot_load_corrupt_total")
        warnings.warn(f"unreadable AOT manifest at {manifest_path!r} "
                      f"({e!r}); warm start falls back to lowering",
                      stacklevel=2)
        return ()
    key = cache_key(executor)
    if manifest.get("key") != key:
        # a different model / ladder / toolchain: the executables
        # would be the WRONG program — fall back to lowering. A
        # non-dict "key" (version skew, hand edit) is the same miss,
        # not an AttributeError
        _count(executor, "sbt_serving_aot_misses_total")
        found = manifest.get("key")
        if not isinstance(found, dict):
            found = {}
        stale = {k for k in key if found.get(k) != key[k]}
        warnings.warn(
            f"AOT cache at {path!r} was built under a different key "
            f"(mismatched: {sorted(stale)}); warm start falls back to "
            "lowering",
            stacklevel=2,
        )
        return ()
    entries = manifest.get("buckets")
    if not isinstance(entries, dict):
        _count(executor, "sbt_serving_aot_misses_total")
        warnings.warn(
            f"AOT manifest at {path!r} has a malformed buckets "
            "section; warm start falls back to lowering",
            stacklevel=2,
        )
        return ()
    try:
        ordered = sorted((int(b), f) for b, f in entries.items())
    except (TypeError, ValueError):
        # non-numeric bucket keys: same corrupt-manifest miss
        _count(executor, "sbt_serving_aot_misses_total")
        warnings.warn(
            f"AOT manifest at {path!r} has non-numeric bucket keys; "
            "warm start falls back to lowering",
            stacklevel=2,
        )
        return ()
    from spark_bagging_tpu import faults

    restored = []
    tenant = getattr(executor, "model_name", None)
    for bucket, fname in ordered:
        try:
            if faults.ACTIVE is not None:
                # a fired fault lands in the per-bucket handler below:
                # an injected corrupt/truncated read degrades to a
                # counted miss-plus-recompile, never an escaping
                # exception — same contract as real disk rot
                faults.fire("aot.load", tenant=tenant, bucket=bucket)
            with open(os.path.join(path, fname), "rb") as f:
                payload, in_tree, out_tree = pickle.load(f)
            compiled = serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree
            )
        except Exception as e:  # noqa: BLE001 — per-bucket fallback
            _count(executor, "sbt_serving_aot_misses_total")
            _count(executor, "sbt_aot_load_corrupt_total")
            warnings.warn(
                f"failed to restore bucket {bucket} executable from "
                f"{path!r} ({e!r}); it will lower on demand",
                stacklevel=2,
            )
            continue
        if executor._adopt(bucket, compiled):
            restored.append(bucket)
            _count(executor, "sbt_serving_aot_restored_total")
    return tuple(restored)
