"""Versioned model registry with atomic hot-swap.

A serving process outlives any one fitted model: bags get retrained
(fresh data, warm-started growth) and the serving copy must be replaced
WITHOUT dropping in-flight traffic or paying a recompile stall at the
swap instant. The registry owns that lifecycle:

- :meth:`ModelRegistry.register` installs a fitted estimator under a
  name (version 1) wrapped in an
  :class:`~spark_bagging_tpu.serving.executor.EnsembleExecutor`;
- :meth:`ModelRegistry.swap` builds the replacement executor OFF to the
  side, validates it serves the same contract (task, feature width,
  class set), **pre-compiles it on every bucket the live executor has
  active** (so post-swap traffic stays zero-recompile), then replaces
  the entry pointer atomically under the registry lock;
- :meth:`ModelRegistry.load` does the same from a checkpoint directory
  (``utils/checkpoint.load_model``) — the retrain-in-another-process
  hand-off;
- :meth:`ModelRegistry.batcher` returns a
  :class:`~spark_bagging_tpu.serving.batcher.MicroBatcher` whose
  executor is RESOLVED PER MICRO-BATCH from this registry, which is
  what makes a swap atomic from the traffic's point of view: requests
  already forwarded finish on the old executor, the next batch runs on
  the new one, and nothing in between is dropped (tested mid-traffic
  in tests/test_serving.py).
"""

from __future__ import annotations

import os
from typing import Any

import numpy as np

from spark_bagging_tpu import faults, telemetry
from spark_bagging_tpu.analysis.locks import make_lock
from spark_bagging_tpu.serving.executor import EnsembleExecutor
from spark_bagging_tpu.telemetry import capacity as _capacity


class _Entry:
    __slots__ = ("name", "version", "executor", "opts", "quality_opts")

    def __init__(self, name: str, version: int,
                 executor: EnsembleExecutor, opts: dict):
        self.name = name
        self.version = version
        self.executor = executor
        self.opts = opts
        # sticky quality-monitoring options (enable_quality); None
        # means the entry is not drift-monitored
        self.quality_opts: dict | None = None


# sbt-lint: shared-state
class ModelRegistry:
    """Named, versioned serving models. All methods are thread-safe."""

    def __init__(self, **default_executor_opts: Any):
        self._lock = make_lock("serving.registry")
        self._entries: dict[str, _Entry] = {}
        self._default_opts = default_executor_opts
        # deferred import: the health registry lives in the exposition
        # server module, whose http.server import chain (~100ms) only
        # serving processes should pay
        from spark_bagging_tpu.telemetry import server as telemetry_server

        self._health_handle = telemetry_server.register_health_source(
            "model_registry", self, ModelRegistry.health
        )

    def health(self) -> dict:
        """``/healthz`` contribution: the live model/version map. A
        registry is healthy by construction — its job is to always
        hold a consistent serving pointer; per-batcher liveness is the
        batchers' own report."""
        with self._lock:
            models = {
                name: e.version for name, e in self._entries.items()
            }
        return {"healthy": True, "models": models}

    # -- introspection -------------------------------------------------

    def names(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._entries))

    def version(self, name: str) -> int:
        return self._entry(name).version

    def executor(self, name: str) -> EnsembleExecutor:
        """The CURRENT executor for ``name`` (a snapshot — hold the
        return value no longer than one batch if you want swaps to
        take effect)."""
        return self._entry(name).executor

    def model(self, name: str) -> Any:
        return self._entry(name).executor.model

    def _entry(self, name: str) -> _Entry:
        with self._lock:
            try:
                return self._entries[name]
            except KeyError:
                raise KeyError(
                    f"no model registered as {name!r}; have "
                    f"{sorted(self._entries)}"
                ) from None

    # -- lifecycle -----------------------------------------------------

    def _reject_swap(self, name: str, msg: str) -> None:
        """Count + flight-record a contract violation, then raise.
        A rejected swap is an incident (a retrain pipeline shipped an
        incompatible model), so it triggers the armed recorder."""
        telemetry.inc("sbt_serving_swap_rejected_total")
        telemetry.emit_event({
            "kind": "swap_rejected", "model": name, "error": msg,
        })
        raise ValueError(msg)

    def _fail_swap(self, name: str, e: Exception) -> None:
        """A swap that died BUILDING its replacement (AOT restore,
        bucket pre-compile, quality attach) — as opposed to one
        rejected by contract validation. The rollback is structural:
        nothing was committed, so the prior live executor keeps
        serving untouched; counted + flight-recorded as its own
        incident kind."""
        telemetry.inc("sbt_serving_swap_failed_total")
        telemetry.emit_event({
            "kind": "swap_failed", "model": name, "error": repr(e),
        })
        raise RuntimeError(
            f"swap of {name!r} failed before commit ({e!r}); rolled "
            "back — the prior live executor is unchanged and keeps "
            "serving"
        ) from e

    def register(self, name: str, model: Any, *, warmup: bool = False,
                 executable_cache: str | None = None,
                 version: int | None = None,
                 **executor_opts: Any) -> EnsembleExecutor:
        """Install a fitted estimator as version 1 of ``name``.

        ``warmup=True`` compiles the full bucket ladder before the
        method returns (serve-ready, zero compiles afterwards).
        ``executable_cache`` names an AOT cache directory
        (:mod:`~spark_bagging_tpu.serving.aot_cache`) to hydrate
        executables from FIRST — with a full-ladder cache hit, warmup
        compiles nothing and the entry is serve-ready instantly.
        ``executor_opts`` (bucket bounds, donation, serving mesh)
        override the registry defaults and stick to the name across
        swaps. ``version`` installs at an explicit version number —
        the N-process seam (:meth:`load` from a ``serve_config``
        manifest) uses it so every peer process loading one checkpoint
        agrees on the version it serves.
        """
        version = 1 if version is None else int(version)
        if version < 1:
            raise ValueError(f"version must be >= 1, got {version}")
        opts = {**self._default_opts, **executor_opts}
        ex = EnsembleExecutor(model, **opts)
        if executable_cache is not None:
            ex.restore_executables(executable_cache)
        if warmup:
            ex.warmup()
        with self._lock:
            if name in self._entries:
                raise ValueError(
                    f"{name!r} is already registered (version "
                    f"{self._entries[name].version}); use swap() to "
                    "replace it"
                )
            self._entries[name] = _Entry(name, version, ex, opts)
            ex.model_name = name
            ex.model_version = version
        telemetry.inc("sbt_serving_models_registered_total")
        telemetry.set_gauge("sbt_serving_model_version", float(version),
                            labels={"model": name})
        # capacity ledger feed [ISSUE 16]: ownership is established
        # HERE, at commit — any compiles the executor did before this
        # point retroactively become attributed via its fingerprint
        cap = _capacity.ACTIVE
        if cap is not None:
            cap.register_owner(ex)
        return ex

    def swap(self, name: str, model: Any, *, warm: bool = True,
             executable_cache: str | None = None,
             version: int | None = None,
             _equal_version_ok: bool = False,
             **executor_opts: Any) -> EnsembleExecutor:
        """Atomically replace ``name``'s serving model; returns the new
        executor and bumps the version.

        The replacement must serve the same contract (task, feature
        width, and — for classifiers — the exact class set): a swap is
        an invisible model upgrade, not an API change. ``warm=True``
        (default) maps every bucket the live executor has active
        through the NEW executor's ladder and pre-compiles those rungs,
        so the traffic profile that was being served never hits a
        compile stall after the swap (even when ``executor_opts``
        changed the bucket bounds). ``executor_opts`` update the
        entry's sticky options — committed only if the swap succeeds;
        a rejected swap leaves the live entry fully untouched.
        ``executable_cache`` hydrates the replacement from a persisted
        AOT cache before the warm pre-compile pass, so even a
        cold-cache swap stalls only on the rungs the cache missed.
        ``version`` pins the replacement's version number (the
        N-process rolling-swap seam): it must be NEWER than the live
        version — a peer re-loading yesterday's checkpoint over
        today's model is a rollback that must be explicit, not a race
        a load balancer can lose — and the swap is rejected (counted,
        flight-recorded) when it is not. ``_equal_version_ok``
        (internal, used by :meth:`load`) turns the EQUAL-version case
        into a benign no-op returning the live executor instead: two
        peers racing to install the same manifest must converge, not
        record a spurious swap-rejected incident.
        """
        entry = self._entry(name)
        if version is not None and int(version) <= entry.version:
            if _equal_version_ok and int(version) == entry.version:
                return entry.executor
            self._reject_swap(
                name,
                f"stale swap: requested version {int(version)} is not "
                f"newer than the live version {entry.version} "
                "(rollbacks must re-register under a new name or use "
                "an explicitly newer manifest)",
            )
        old = entry.executor
        opts = {**entry.opts, **executor_opts}
        new = EnsembleExecutor(model, **opts)
        if new.task != old.task:
            self._reject_swap(
                name,
                f"swap would change task {old.task!r} -> {new.task!r}",
            )
        if new.n_features != old.n_features:
            self._reject_swap(
                name,
                f"swap would change feature width {old.n_features} -> "
                f"{new.n_features}",
            )
        if old.classes_ is not None and not np.array_equal(
            np.asarray(old.classes_), np.asarray(new.classes_)
        ):
            self._reject_swap(
                name,
                "swap would change the served class set; register the "
                "new label space under a new name instead",
            )
        quality_gap: Exception | None = None
        try:
            if executable_cache is not None:
                new.restore_executables(executable_cache)
            if warm:
                from spark_bagging_tpu.serving.buckets import bucket_for

                for b in old.compiled_buckets:
                    if faults.ACTIVE is not None:
                        faults.fire("registry.swap.precompile",
                                    bucket=b)
                    # translate the observed traffic profile into the
                    # new executor's ladder (bounds may differ): the
                    # row counts that used to run in bucket b land in
                    # its image rung
                    new._build(bucket_for(
                        b, new.min_bucket_rows, new.max_batch_rows
                    ))
            if entry.quality_opts is not None:
                # sticky drift monitoring attaches to the replacement
                # BEFORE commit: an attach failure rolls the swap back
                # (prior executor + its monitor untouched), and the
                # replacement is monitored from its very first batch —
                # no commit-to-attach gap. One carve-out: a
                # replacement with no fit-time profile (stream fit,
                # older checkpoint) can never be monitored, and
                # blocking a model upgrade on an optional plane is
                # wrong — that case swaps anyway and warns below.
                q_opts = dict(entry.quality_opts)
                q_opts.setdefault("labels", {"model": str(name)})
                try:
                    self._attach_quality(new, q_opts)
                except ValueError as e:
                    quality_gap = e
        # sbt-lint: disable=swallowed-fault — _fail_swap counts, flight-records, and re-raises (the rollback path)
        except Exception as e:  # noqa: BLE001 — rollback, not delivery
            self._fail_swap(name, e)
        stale_live = None
        live_ex = None
        with self._lock:
            # re-read under the lock: racing swaps must serialize into
            # a strict version order, last one in place — and an
            # explicit (manifest) version re-checks staleness HERE,
            # where the ordering is decided, not just at entry
            entry = self._entries[name]
            if version is not None and int(version) <= entry.version:
                stale_live = entry.version
                live_ex = entry.executor
            else:
                entry.executor = new
                entry.opts = opts
                entry.version = (entry.version + 1 if version is None
                                 else int(version))
                version = entry.version
                new.model_name = name
                new.model_version = version
        if stale_live is not None:
            if _equal_version_ok and int(version) == stale_live:
                # a racing peer installed the very manifest we carry:
                # the documented poller convergence, not an incident
                return live_ex
            self._reject_swap(
                name,
                f"stale swap: requested version {int(version)} is not "
                f"newer than the live version {stale_live} (a racing "
                "peer already installed it)",
            )
        telemetry.inc("sbt_serving_swaps_total")
        telemetry.set_gauge("sbt_serving_model_version", float(version),
                            labels={"model": name})
        # not a flight-recorder trigger (a swap is routine), but it IS
        # timeline material: the fleet incident correlator lines swap
        # commits up against the dumps/alerts/sheds around them
        telemetry.emit_event({
            "kind": "model_swapped", "model": name,
            "version": int(version),
        })
        # capacity ledger feed [ISSUE 16]: runs ONLY on the commit
        # path — a failed swap raised out of _fail_swap above, so the
        # replacement's fingerprint never acquires an owner and its
        # pre-compile cache entries stay unattributed (the no-leak
        # contract, regression-tested). The outgoing executor is
        # retired, not erased: its resident entries keep their owner
        # for eviction attribution.
        cap = _capacity.ACTIVE
        if cap is not None:
            cap.register_owner(new, retired_fingerprint=old.fingerprint)
        if quality_gap is not None:
            # the one attach failure that does NOT roll back: a
            # replacement with no fit-time quality_profile_ (stream
            # fit, older checkpoint) can never be monitored — the
            # model upgrade ships, loudly unmonitored
            import warnings

            warnings.warn(
                f"swap of {name!r} succeeded but drift monitoring "
                f"could not re-attach: {quality_gap} (version "
                f"{version} serves UNMONITORED; fit the replacement "
                "with this build or disable_quality first)",
                RuntimeWarning,
                stacklevel=2,
            )
        return new

    def enable_quality(self, name: str,
                       **monitor_opts: Any):
        """Attach a drift monitor (``telemetry.quality``) to ``name``'s
        live executor and make it sticky: every future :meth:`swap` /
        :meth:`load` re-attaches a fresh monitor to the replacement
        executor (new model ⇒ new reference ⇒ fresh sketches).
        ``monitor_opts`` are ``QualityMonitor`` options
        (``refresh_every``, ``disagreement_every``, ...) plus an
        optional ``profile=`` override — which applies to the CURRENT
        executor only and is never sticky: a swapped-in model is
        scored against its own fit-time ``quality_profile_``, not a
        reference authored for its predecessor. Returns the monitor.
        """
        entry = self._entry(name)
        with self._lock:
            # sticky flag FIRST, executor snapshot under the same
            # lock: a swap() interleaving after this block either saw
            # the flag (and re-attaches to its new executor) or
            # committed before our read (and we attach to the new
            # executor) — either way the LIVE model ends up monitored.
            # 'profile' and 'monitor' are per-attach, never sticky: a
            # swapped-in model must be scored against its OWN
            # reference with FRESH sketches, and replaying a caller's
            # monitor= instance would re-install the predecessor's
            # profile and accumulated counts verbatim.
            entry.quality_opts = {
                k: v for k, v in monitor_opts.items()
                if k not in ("profile", "monitor")
            }
            ex = entry.executor
        return self._attach_quality(ex, monitor_opts)

    def disable_quality(self, name: str) -> None:
        """Detach ``name``'s drift monitor and clear the sticky flag."""
        entry = self._entry(name)
        with self._lock:
            # clear-then-snapshot under the lock (mirror of
            # enable_quality): a racing swap either sees the cleared
            # flag (no re-attach) or committed first (we detach its
            # new executor) — a model can never stay monitored after
            # disable_quality returns
            entry.quality_opts = None
            ex = entry.executor
        ex.detach_quality()

    @staticmethod
    def _attach_quality(executor: EnsembleExecutor, opts: dict):
        from spark_bagging_tpu.telemetry import quality

        return quality.attach(executor, **opts)

    #: subdirectory of a checkpoint dir where :meth:`save` persists the
    #: bucket executables and :meth:`load` looks for them
    AOT_SUBDIR = "serving_aot"
    #: the serving manifest :meth:`save` writes next to the weights —
    #: the N-process seam: everything a fresh process needs to serve
    #: this checkpoint exactly as the saver did (executor config, mesh
    #: shape, version), without the operator re-specifying any of it
    SERVE_CONFIG = "serve_config.json"

    def _read_serve_config(self, path: str) -> dict | None:
        """The ``serve_config.json`` manifest at ``path``, or None
        (absent or unreadable — a config-less checkpoint is an older
        saver's, not an error)."""
        import json

        cfg_path = os.path.join(path, self.SERVE_CONFIG)
        if not os.path.isfile(cfg_path):
            return None
        try:
            with open(cfg_path) as f:
                cfg = json.load(f)
        except (OSError, ValueError) as e:
            import warnings

            warnings.warn(
                f"unreadable serve_config at {cfg_path!r} ({e!r}); "
                "loading with caller/registry executor options only",
                stacklevel=3,
            )
            return None
        return cfg if isinstance(cfg, dict) else None

    def _opts_from_config(self, cfg: dict,
                          executor_opts: dict) -> dict:
        """Merge a serve_config's executor section UNDER the caller's
        explicit options. The persisted mesh SHAPE is reconstructed
        into a live mesh when this process has the devices for it;
        otherwise the process serves single-device with a warning —
        the persisted mesh executables then restore as counted AOT
        misses, never as wrong answers."""
        merged: dict[str, Any] = {}
        section = cfg.get("executor")
        if not isinstance(section, dict):
            return executor_opts
        for k in ("min_bucket_rows", "max_batch_rows", "donate_input"):
            if section.get(k) is not None:
                merged[k] = section[k]
        shape = section.get("mesh")
        if (
            shape
            and "mesh" not in executor_opts
            and "mesh" not in self._default_opts
        ):
            from spark_bagging_tpu.parallel.mesh import make_mesh

            try:
                import jax

                data, replica = int(shape[0]), int(shape[1])
                devices = list(jax.devices())
                need = data * replica
                # a host with MORE devices than the manifest's mesh is
                # the natural rolling-upgrade case: build the recorded
                # shape over a prefix of the devices rather than
                # demanding an exact count (make_mesh's default)
                kwargs = ({"devices": devices[:need]}
                          if len(devices) >= need else {})
                merged["mesh"] = make_mesh(data=data, replica=replica,
                                           **kwargs)
            except (ValueError, TypeError, IndexError) as e:
                # IndexError: a truncated/hand-edited "mesh" entry —
                # corrupt manifests degrade, they never crash a load
                import warnings

                warnings.warn(
                    f"serve_config names a {shape} serving mesh this "
                    f"process cannot build ({e}); serving "
                    "single-device (persisted mesh executables will "
                    "restore as counted AOT misses)",
                    stacklevel=3,
                )
        return {**merged, **executor_opts}

    def load(self, name: str, path: str, *, warm: bool = True,
             executable_cache: str | None = "auto",
             **executor_opts: Any) -> EnsembleExecutor:
        """Register-or-swap ``name`` from a checkpoint directory saved
        with :meth:`save` (or ``estimator.save()`` /
        ``utils/checkpoint.save_model``) — the hand-off seam from a
        retraining job AND between peer serving processes.
        ``executor_opts`` apply either way: on an existing name they
        ride the swap (committed to the entry's sticky options only on
        success).

        When the directory carries a ``serve_config.json`` manifest
        (:meth:`save` writes one), its executor configuration — bucket
        bounds, donation, serving-mesh shape — is adopted underneath
        any caller-explicit options, and its VERSION is adopted
        outright: M peer processes loading the same checkpoint all
        serve the same version number, a re-load of the already-live
        version is an idempotent no-op, and a load of an OLDER
        manifest than the live version is rejected loudly (a rolling
        swap must only ever move forward; rollbacks re-register under
        a new name or ship a newer manifest).

        Executables ride alongside weights: ``executable_cache="auto"``
        (default) hydrates from ``<path>/serving_aot`` when
        :meth:`save` left one there — a fresh serving process reaches
        zero-recompile steady state at startup instead of after
        warmup. A key mismatch (different model, ladder, mesh shape,
        jax version, backend) silently falls back to lowering. Pass
        ``None`` to skip, or an explicit directory to use a cache kept
        elsewhere.
        """
        from spark_bagging_tpu.utils.checkpoint import load_model

        cfg = self._read_serve_config(path)
        version: int | None = None
        # kept verbatim so stale-manifest detection below can fall all
        # the way back to what the CALLER asked for — a torn save's
        # manifest must donate neither its version nor its executor
        # configuration
        caller_opts = dict(executor_opts)
        if cfg is not None:
            v = cfg.get("version")
            if isinstance(v, int) and v >= 1:
                version = v
            executor_opts = self._opts_from_config(cfg, executor_opts)
        with self._lock:
            entry = self._entries.get(name)
            live_version = entry.version if entry is not None else None
            live_executor = entry.executor if entry is not None else None
        if (
            version is not None
            and live_version is not None
            and version == live_version
        ):
            # idempotent convergence: a peer polling the checkpoint
            # dir re-loads the version it already serves — a no-op,
            # not an error (and not a spurious version bump)
            return live_executor
        model = load_model(path)
        if cfg is not None and isinstance(
                cfg.get("model_fingerprint"), str):
            # torn-save detection: the manifest names the weights it
            # was committed with; a mismatch means a save died between
            # its checkpoint write and its manifest rename. The
            # weights themselves are a complete, valid checkpoint —
            # serve them — but the manifest's version/config describe
            # a DIFFERENT publish and must not be adopted
            from spark_bagging_tpu.serving import program_cache as _pcache

            if _pcache.fingerprint_model(model) != cfg["model_fingerprint"]:
                import warnings

                warnings.warn(
                    f"serve_config at {path!r} does not match the "
                    "checkpoint weights next to it (a save() was "
                    "killed before its manifest commit); ignoring the "
                    "stale manifest's version AND executor config — "
                    "loading as an ordinary register/swap with the "
                    "caller's options",
                    stacklevel=2,
                )
                version = None
                executor_opts = caller_opts
        if executable_cache == "auto":
            auto = os.path.join(path, self.AOT_SUBDIR)
            executable_cache = auto if os.path.isdir(auto) else None
        if live_version is None:
            try:
                return self.register(name, model, warmup=warm,
                                     executable_cache=executable_cache,
                                     version=version,
                                     **executor_opts)
            except ValueError:
                # register-or-swap must be race-safe: another load()
                # may have installed the name between our check and the
                # register — only that race falls through to swap
                with self._lock:
                    if name not in self._entries:
                        raise
        # _equal_version_ok: two peers racing to install the same
        # manifest version must CONVERGE (the loser gets the winner's
        # executor back), not crash with a spurious stale-swap
        # incident — including the register-race fallthrough above,
        # where the loser arrives here carrying the same version the
        # winner just installed
        return self.swap(name, model, warm=warm,
                         executable_cache=executable_cache,
                         version=version,
                         _equal_version_ok=version is not None,
                         **executor_opts)

    def save(self, name: str, path: str, *, compress: bool | str = "auto",
             executables: bool = True) -> None:
        """Checkpoint ``name``'s live model to directory ``path`` —
        and, with ``executables=True``, persist its compiled bucket
        executables into ``<path>/serving_aot`` so :meth:`load` in a
        fresh process warm-starts without a single compile. The
        executable pass is best-effort: an executor with nothing
        compiled yet, or a backend without executable serialization,
        saves weights only.

        A ``serve_config.json`` manifest is always written: the
        version + executor configuration a peer process's :meth:`load`
        adopts (see there for the rolling-swap rules). Donation is
        persisted as the entry's CONFIGURED value, not the resolved
        boolean — a checkpoint saved on CPU must not pin donation off
        for the TPU peer that loads it.

        Torn-write safety: each component writes atomically (the
        checkpoint via its tmp+swap with a ``.old`` recovery slot, the
        AOT dir via tmp+rename, the manifest via tmp+rename), the
        manifest rename is LAST and is the save's commit point, and
        the manifest binds itself to the weights it describes via
        ``model_fingerprint``. A kill at ANY point between the steps
        (the ``registry.save.*`` / ``checkpoint.write`` / ``aot.save``
        fault-injection sites) leaves a directory :meth:`load` serves
        correctly: a stale manifest is detected by fingerprint and
        ignored (warned), mismatched AOT entries restore as counted
        misses, and the previously published version stays loadable —
        partial artifacts are never wrong answers."""
        import json

        from spark_bagging_tpu.utils.checkpoint import save_model

        entry = self._entry(name)
        with self._lock:
            ex = entry.executor
            version = entry.version
            donate_opt = entry.opts.get("donate_input")
        save_model(ex.model, path, compress=compress)
        if faults.ACTIVE is not None:
            faults.fire("registry.save.checkpoint")
        if executables and ex.compiled_buckets:
            ex.save_executables(os.path.join(path, self.AOT_SUBDIR))
        if faults.ACTIVE is not None:
            faults.fire("registry.save.aot")
        cfg = {
            "format": 1,
            "name": name,
            "version": version,
            "task": ex.task,
            "n_features": ex.n_features,
            # binds this manifest to the exact weights it was written
            # next to: load() ignores (and warns about) a manifest
            # whose fingerprint does not match the checkpoint — the
            # torn-save signature
            "model_fingerprint": ex.fingerprint,
            "executor": {
                "min_bucket_rows": ex.min_bucket_rows,
                "max_batch_rows": ex.max_batch_rows,
                "donate_input": donate_opt,
                "mesh": (list(ex.mesh_shape)
                         if ex.mesh_shape is not None else None),
            },
            "warm_buckets": [int(b) for b in ex.compiled_buckets],
            "quality": entry.quality_opts is not None,
        }
        tmp = os.path.join(path, f"{self.SERVE_CONFIG}.tmp")
        with open(tmp, "w") as f:
            json.dump(cfg, f, indent=2)
        if faults.ACTIVE is not None:
            # the last kill window: everything written, nothing
            # committed — load() must still serve the prior manifest's
            # version (or detect the staleness by fingerprint)
            faults.fire("registry.save.manifest")
        os.replace(tmp, os.path.join(path, self.SERVE_CONFIG))

    def batcher(self, name: str, **batcher_opts: Any):
        """A micro-batcher bound to THIS registry entry by name: each
        micro-batch resolves the executor afresh, so ``swap()`` takes
        effect at the next batch boundary with no dropped requests."""
        from spark_bagging_tpu.serving.batcher import MicroBatcher

        self._entry(name)  # fail fast on unknown names
        return MicroBatcher(lambda: self.executor(name), **batcher_opts)
