"""Micro-batching request coalescer — many submitters, one TPU forward.

Online traffic arrives as many small concurrent requests; the paper's
aggregation story ("vote/mean over replicas is ONE batched forward")
only pays when those requests ride one program launch. The
``MicroBatcher`` owns a bounded request queue and a single worker
thread: the worker takes the first waiting request, keeps gathering
until ``max_delay_ms`` elapses or ``max_batch_rows`` accumulate,
packs the request blocks into the executor's ragged slab plan
(``EnsembleExecutor.forward_parts`` — row-offset scatter, no
concatenate-then-pad double copy), then delivers each block's slice
of the output to its per-request future.

Coalescing only pays when there is someone to coalesce WITH. At
concurrency 1 the queue+worker+future handoff is pure overhead, so
the batcher adapts (**adaptive direct dispatch**): after a streak of
one-request batches proves the delay window is buying nothing, a
submit that finds nothing in flight runs the forward inline on the
caller's thread — naive-dispatch cost, no queue, no handoff. The
decision is a lock-light occupancy counter plus the singleton streak
(one short ``Lock`` held for counter ops only); the first contended
submit, or the first multi-request batch, revokes direct mode on the
spot. Starting in coalescing mode matters: a single-threaded async
dispatcher keeping N futures in flight would be SERIALIZED by inline
serving (each submit would resolve before the next), and the
evidence rule keeps it coalescing because its batches are never
singletons. ``sbt_serving_direct_dispatch_total`` /
``sbt_serving_coalesced_total`` (and the ``path`` label on the
latency histogram) make the split observable.

Contracts that matter under load:

- **Backpressure is explicit.** ``submit`` never blocks: a full queue
  raises :class:`Overloaded` immediately (and counts
  ``sbt_serving_overloaded_total`` plus
  ``sbt_serving_shed_total{reason="overload"}``) so callers shed load
  at the edge instead of silently queueing into timeout territory.
- **Deadlines shed distinctly.** ``submit(X, deadline_ms=...)`` stamps
  a per-request deadline; a request still queued when its batch is
  claimed past the deadline fails with :class:`DeadlineExceeded`
  (``sbt_serving_shed_total{reason="deadline"}``) — "too slow" is a
  different incident than "too full", and the shed accounting keeps
  them apart.
- **Failure is per-request, not fatal.** An executor exception fails
  at most the requests that caused it: transient failures (anything
  raised with ``transient=True``, e.g. ``faults.TransientFault``)
  retry with bounded exponential backoff (``retries=``,
  ``sbt_serving_retries_total``), and a batch that still fails
  **bisects** — each half is served independently, recursively, until
  the one poisoned request fails alone
  (``sbt_serving_batch_bisects_total``) while its batch-mates are
  served normally. The worker keeps serving through all of it.
- **The worker is supervised.** A crash that escapes the per-batch
  guard (a wedged sink, an injected fault) is caught by the
  supervisor: the crash is counted + flight-recorded and a fresh
  worker thread takes over (``sbt_serving_worker_restarts_total``).
  ``crash_loop_threshold`` crashes inside ``crash_loop_window_s``
  instead trip **degraded reject mode**: one ``serving_crash_loop``
  flight dump, ``/healthz`` 503, and every further ``submit()`` shed
  with :class:`Degraded`
  (``sbt_serving_shed_total{reason="degraded"}``) until an operator
  calls :meth:`MicroBatcher.revive`.
- **Hot-swap-safe.** The executor is resolved from a provider ONCE per
  micro-batch, so a registry ``swap()`` takes effect at the next batch
  boundary while requests already forwarded finish on the executor
  they started with — no request is ever dropped by a swap.
- **Every request is traceable.** ``submit()`` mints a trace context
  (``telemetry.tracing``) exposed as ``future.trace``: after the
  future resolves, ``future.trace.breakdown`` attributes the latency
  (``queue_ms``/``batch_ms``/``forward_ms``/``total_ms``) and names
  the batch (``batch_size``, ``bucket``, ``model_version``); span
  events carry the ids, and batch failures / overload rejections emit
  flight-recorder trigger events. All of it vanishes when telemetry
  is disabled (``future.trace is None``).
- **The arrival stream is capturable.** While an arrival consumer is
  active (a recording ``telemetry.workload.WorkloadRecorder`` or an
  open ``capture()`` window), ``submit()`` also emits one
  ``serving_request`` event (rows, width, dtype, bucket, queue depth,
  monotonic arrival stamp) — the stream the workload recorder
  serializes into replayable ``*.workload.jsonl`` files. No consumer,
  no event, no cost — an armed flight recorder alone does not count
  (it deliberately ignores arrival events).
- **Replay can step it deterministically.** ``threaded=False`` starts
  no worker thread; the owner drives batching explicitly with
  :meth:`run_pending`, which drains the queue into batches by the
  same row rule the worker uses — but on the caller's thread, with no
  timing dependence, so a replay harness gets identical batch
  composition (and therefore bitwise-identical outputs) on every run.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from queue import Empty, Full, Queue
from typing import Any, Callable

import numpy as np

from spark_bagging_tpu import faults, telemetry
from spark_bagging_tpu.analysis.locks import make_lock
from spark_bagging_tpu.serving.buckets import bucket_for, pack_plan
from spark_bagging_tpu.telemetry import perf as _perf
from spark_bagging_tpu.telemetry import tracing

_SHUTDOWN = object()


class Overloaded(RuntimeError):
    """The batcher's request queue is full — shed this request.

    Raised by :meth:`MicroBatcher.submit` instead of blocking: under
    sustained overload a bounded queue must reject at the edge, or
    every request degrades to worst-case latency together.
    """


class DeadlineExceeded(RuntimeError):
    """A request's ``deadline_ms`` expired while it waited in queue —
    shed as "too slow", distinct from :class:`Overloaded`'s "too full"
    (separate ``sbt_serving_shed_total{reason=}`` labels and event
    kinds)."""


class Degraded(RuntimeError):
    """The batcher is in degraded reject mode: its worker crash-looped
    (``crash_loop_threshold`` crashes inside ``crash_loop_window_s``)
    and requests are shed at the edge until an operator calls
    :meth:`MicroBatcher.revive` after remediation."""


class _Failed:
    """Per-request failure sentinel inside a served batch's outputs —
    how retry/bisect recovery reports 'this one request failed' without
    failing its batch-mates."""

    __slots__ = ("error",)

    def __init__(self, error: BaseException):
        self.error = error


class _Request:
    __slots__ = ("X", "n", "mode", "future", "t_submit", "trace",
                 "deadline_t", "poisoned")

    def __init__(self, X: np.ndarray, mode: str,
                 trace: "tracing.TraceContext | None",
                 deadline_t: float | None = None):
        self.X = X
        self.n = X.shape[0]
        self.mode = mode
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        # absolute deadline on the batcher's clock (None: no deadline)
        self.deadline_t = deadline_t
        # set by an armed fault plan (chaos experiments only): this
        # request's forward fails until bisection isolates it
        self.poisoned = False
        # per-request trace context (None when telemetry is disabled);
        # mirrored onto the future so callers can read
        # `future.trace.breakdown` after the result resolves
        self.trace = trace
        self.future.trace = trace  # type: ignore[attr-defined]


# sbt-lint: shared-state
class MicroBatcher:
    """Coalesce concurrent ``submit()`` calls into bucketed forwards.

    ``executor`` is an :class:`~spark_bagging_tpu.serving.executor.
    EnsembleExecutor` — or a zero-arg callable returning the current
    one (the registry's hot-swap hook).

    ``max_delay_ms`` bounds the extra latency any request pays waiting
    for batch-mates; ``max_batch_rows`` bounds one forward's row count;
    ``max_queue`` bounds requests admitted but not yet forwarded
    (beyond it, :class:`Overloaded`).

    ``idle_flush_ms`` is how long the worker lingers on an EMPTY queue
    before launching what it has. Closed-loop clients (submit, wait,
    repeat) all go quiet once their wave is enqueued — waiting out the
    full ``max_delay_ms`` window after that is pure added latency with
    zero extra coalescing, so the default flushes fast; raise it toward
    ``max_delay_ms`` when clients are open-loop and stragglers trickle
    in, lower it to 0 to launch the instant the queue empties.

    ``direct_dispatch`` (default: on exactly when ``threaded``) is the
    adaptive low-concurrency fast path: once
    :data:`DIRECT_AFTER_SINGLETONS` consecutive batches have carried a
    single request each (proof that the delay window coalesces
    nothing), a ``submit()`` that finds nothing in flight and an empty
    queue skips queue + worker + future handoff entirely and runs the
    forward INLINE on the caller's thread — concurrency 1 pays
    naive-dispatch cost instead of a coalescing window it can never
    benefit from. The first contended submit or multi-request batch
    revokes the mode, and traffic coalesces again until the streak
    re-earns it. Stepped mode forces it off — replay determinism
    requires batch composition to be a pure function of the queue
    contents.

    ``threaded=False`` is stepped mode: no worker thread runs, and the
    owner serves queued requests synchronously via :meth:`run_pending`
    (the deterministic-replay seam — see ``benchmarks/replay.py``).

    Robustness knobs: ``retries`` bounds how many times a TRANSIENT
    forward failure (``transient=True`` on the exception, e.g.
    ``faults.TransientFault``) is retried, with
    ``retry_backoff_ms``-based exponential backoff between attempts;
    ``bisect_on_error`` (default on) splits a persistently failing
    multi-request batch in half and serves each half independently so
    one poisoned request fails alone. ``supervise`` (default on, with
    ``crash_loop_threshold`` / ``crash_loop_window_s``) restarts a
    crashed worker thread and trips degraded reject mode on a crash
    loop. ``clock`` overrides the monotonic clock used for DEADLINE
    math only (the replay harness injects its virtual clock there so
    deadline sheds are deterministic); latency timing always uses the
    real clock.
    """

    def __init__(
        self,
        executor: Any,
        *,
        max_delay_ms: float = 2.0,
        max_batch_rows: int = 2048,
        max_queue: int = 256,
        idle_flush_ms: float = 0.25,
        threaded: bool = True,
        direct_dispatch: bool | None = None,
        retries: int = 0,
        retry_backoff_ms: float = 5.0,
        bisect_on_error: bool = True,
        supervise: bool = True,
        crash_loop_threshold: int = 3,
        crash_loop_window_s: float = 30.0,
        clock: Callable[[], float] | None = None,
    ):
        if max_delay_ms < 0 or idle_flush_ms < 0:
            raise ValueError(
                f"delays must be >= 0, got max_delay_ms={max_delay_ms}, "
                f"idle_flush_ms={idle_flush_ms}"
            )
        if max_batch_rows < 1 or max_queue < 1:
            raise ValueError("max_batch_rows and max_queue must be >= 1")
        if retries < 0 or retry_backoff_ms < 0:
            raise ValueError(
                f"retries and retry_backoff_ms must be >= 0, got "
                f"{retries}, {retry_backoff_ms}"
            )
        if crash_loop_threshold < 1 or crash_loop_window_s <= 0:
            raise ValueError(
                "need crash_loop_threshold >= 1 and "
                "crash_loop_window_s > 0"
            )
        if callable(executor) and not hasattr(executor, "forward"):
            self._resolve: Callable[[], Any] = executor
        else:
            self._resolve = lambda: executor
        # contract snapshot: the registry's swap validation guarantees
        # task and feature width are invariant per entry, so submit()
        # validates against this snapshot instead of resolving the
        # executor (a registry-lock acquisition) on every request
        ex0 = self._resolve()
        self._n_features = int(ex0.n_features)
        self._task = ex0.task
        # bucket-ladder snapshot for the arrival-stream events: swap
        # validation keeps task/width invariant per entry, and bucket
        # bounds are registry-sticky options, so capture-time bucket
        # attribution from this snapshot stays honest across swaps
        # (plain callables without a ladder record bucket=None)
        if hasattr(ex0, "min_bucket_rows") and hasattr(
                ex0, "max_batch_rows"):
            self._bucket_bounds = (int(ex0.min_bucket_rows),
                                   int(ex0.max_batch_rows))
        else:
            self._bucket_bounds = None
        if direct_dispatch is None:
            direct_dispatch = threaded
        elif direct_dispatch and not threaded:
            raise ValueError(
                "direct_dispatch requires threaded=True; stepped mode "
                "is the deterministic-replay seam and must keep batch "
                "composition a pure function of the queue"
            )
        self._direct = bool(direct_dispatch)
        # adaptive-dispatch state, all guarded by a dedicated lock held
        # for the counter ops only. Direct mode is EARNED, not assumed:
        # a batcher starts coalescing and demotes to inline serving
        # only after DIRECT_AFTER_SINGLETONS consecutive one-request
        # batches prove there is nobody to coalesce with. (Occupancy
        # alone cannot see a single-threaded async dispatcher that
        # wants 16 futures in flight — inline serving would serialize
        # it — but such a dispatcher produces multi-request batches,
        # which is exactly the signal that keeps coalescing on.)
        self._occupancy = 0
        self._mode_direct = False
        self._singleton_streak = 0
        self._occ_lock = make_lock("serving.batcher.occupancy")
        self.max_delay_s = max_delay_ms / 1e3
        self.idle_flush_s = idle_flush_ms / 1e3
        self.max_batch_rows = int(max_batch_rows)
        self._retries = int(retries)
        self._retry_backoff_s = retry_backoff_ms / 1e3
        self._bisect = bool(bisect_on_error)
        # deadline clock: injectable so the replay harness can drive
        # expiry off its virtual clock (determinism); everything else
        # (latency, stall age) stays on the real monotonic clock
        self._clock: Callable[[], float] = clock or time.monotonic
        self._q: Queue = Queue(maxsize=int(max_queue))
        self._stop = threading.Event()
        self._closed = False
        self._close_lock = make_lock("serving.batcher.close")
        # worker supervision state, guarded by its own short lock: the
        # crash history ring sizes itself to the loop threshold, and
        # _degraded is the reject-mode flag submit() reads unlocked
        # (benign: a momentarily stale read sheds or admits one request
        # at the mode boundary)
        self._threaded = bool(threaded)
        self._supervise = bool(supervise) and threaded
        self._crash_window_s = float(crash_loop_window_s)
        self._crash_ts: deque[float] = deque(maxlen=int(crash_loop_threshold))
        self._degraded = False
        self._sup_lock = make_lock("serving.batcher.supervisor")
        # health facts for /healthz: single-writer fields (the worker
        # thread); readers tolerate a momentarily stale float. Seeded
        # at construction so a cold-start burst (queue pinned while
        # the first forward compiles) gets the full STALL_S grace
        # before /healthz calls it a stall
        self._t_last_batch: float = time.monotonic()
        self._worker: threading.Thread | None = None
        if threaded:
            self._worker = threading.Thread(
                target=self._worker_main, daemon=True,
                name="serving-batcher"
            )
            self._worker.start()
        # deferred import: the health registry lives in the exposition
        # server module, whose http.server import chain (~100ms) only
        # serving processes should pay. Register AFTER the worker
        # exists — health() reads it, and a scrape can land the
        # instant registration returns
        from spark_bagging_tpu.telemetry import server as telemetry_server

        self._health_handle = telemetry_server.register_health_source(
            "batcher", self, MicroBatcher.health
        )

    # -- client side ---------------------------------------------------

    # sbt-lint: hot-path
    def submit(self, X, *, mode: str = "aggregate",
               deadline_ms: float | None = None,
               trace: "tracing.TraceContext | None" = None) -> Future:
        """Enqueue one request; returns a ``concurrent.futures.Future``.

        ``mode="aggregate"`` resolves to the executor's raw aggregated
        output (probabilities / predictions); ``mode="predict"``
        resolves to class labels (classification) or predictions
        (regression). ``deadline_ms`` bounds how long the request may
        WAIT: if it is still queued when its batch is claimed past the
        deadline, its future fails with :class:`DeadlineExceeded`
        instead of being served late. Raises :class:`Overloaded` when
        the queue is full, :class:`Degraded` in crash-loop reject
        mode, and ``RuntimeError`` after :meth:`close`. ``trace``
        threads an upstream-minted :class:`~..telemetry.tracing.
        TraceContext` (the tenancy fleet's, carrying pre-batcher
        journey timings) through instead of minting a fresh one here
        — one request, one trace, across every pipeline stage.

        With direct dispatch enabled (the threaded-mode default), an
        idle batcher serves the request INLINE before returning — the
        future comes back already resolved, and concurrent arrivals
        during the inline serve take the coalescing queue.
        """
        if mode not in ("aggregate", "predict"):
            raise ValueError(f"unknown mode {mode!r}")
        if self._closed:
            raise RuntimeError("MicroBatcher is closed")
        if self._degraded:
            # crash-loop reject mode: shed at the edge, distinctly —
            # a load balancer reading /healthz routes away; anything
            # that still lands here must not hang on a dead worker
            telemetry.inc("sbt_serving_shed_total",
                          labels={"reason": "degraded"})
            telemetry.emit_event({
                "kind": "serving_degraded_reject",
                "rows": int(np.asarray(X).shape[0]) if hasattr(
                    X, "shape") else None,
            })
            raise Degraded(
                "serving is in degraded reject mode (worker crash "
                "loop); call revive() after remediation"
            )
        X = np.ascontiguousarray(X, dtype=np.float32)
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2 or X.shape[1] != self._n_features:
            raise ValueError(
                f"X must be (n, {self._n_features}), got {X.shape}"
            )
        if X.shape[0] == 0:
            raise ValueError("X has no rows")
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(
                f"deadline_ms must be > 0, got {deadline_ms}"
            )
        if trace is None:
            trace = (tracing.request_context() if telemetry.enabled()
                     else None)
        deadline_t = (self._clock() + deadline_ms / 1e3
                      if deadline_ms is not None else None)
        req = _Request(X, mode, trace, deadline_t)
        if faults.ACTIVE is not None and faults.fire(
                "batcher.submit", rows=req.n):
            # an armed chaos plan marked this request poisoned: its
            # batch's forward fails until bisection isolates it
            req.poisoned = True
        if self._direct:
            # adaptive path decision: serve inline iff direct mode has
            # been earned AND nothing else is in flight — one short
            # lock for the counter ops only. A contended submit while
            # in direct mode is the concurrency signal: revoke the
            # mode on the spot and let the coalescer take over.
            with self._occ_lock:
                direct = (self._mode_direct and self._occupancy == 0
                          and self._q.empty())
                if direct:
                    self._occupancy += 1
                elif self._mode_direct:
                    self._mode_direct = False
                    self._singleton_streak = 0
            if direct:
                return self._serve_direct(req)
        with tracing.use(trace):
            with telemetry.span("serving_enqueue", rows=req.n):
                try:
                    self._q.put_nowait(req)
                except Full:
                    telemetry.inc("sbt_serving_overloaded_total")
                    telemetry.inc("sbt_serving_shed_total",
                                  labels={"reason": "overload"})
                    telemetry.emit_event({
                        "kind": "serving_overloaded",
                        "trace_id": trace.trace_id if trace else None,
                        "rows": req.n,
                        "max_queue": self._q.maxsize,
                    })
                    raise Overloaded(
                        f"serving queue full ({self._q.maxsize} requests "
                        "waiting); retry with backoff or raise max_queue"
                    ) from None
        if self._closed and req.future.cancel():
            # raced close(): its drain may already have run, so nobody
            # would ever serve this request — a successful cancel means
            # no worker claimed it (claims flip it to RUNNING, where
            # cancel() returns False and the request is served anyway);
            # fail fast instead of hanging the caller
            raise RuntimeError("MicroBatcher closed during submit")
        if self._degraded and req.future.cancel():
            # raced the crash-loop trip (same pattern as close above):
            # the degraded drain is one-shot and may already have run,
            # and no worker will ever claim this request — shed it now
            # instead of stranding the caller on a dead worker
            telemetry.inc("sbt_serving_shed_total",
                          labels={"reason": "degraded"})
            raise Degraded(
                "serving entered degraded reject mode during submit"
            )
        if telemetry.enabled():
            telemetry.inc("sbt_serving_requests_total")
            telemetry.set_gauge("sbt_serving_queue_depth",
                                self._q.qsize())
            if telemetry.arrival_events_wanted():
                # the capturable arrival stream (workload recorders,
                # open capture files): dict built only when a consumer
                # is listening — an always-armed flight recorder alone
                # (the standard serving deployment) costs nothing here
                bucket = None
                if self._bucket_bounds is not None:
                    bucket = bucket_for(req.n, *self._bucket_bounds)
                telemetry.emit_event({
                    "kind": "serving_request",
                    "rows": req.n,
                    "width": self._n_features,
                    "dtype": str(req.X.dtype),
                    "bucket": bucket,
                    "queue_depth": self._q.qsize(),
                    "trace_id": trace.trace_id if trace else None,
                    "t_mono": time.monotonic(),
                })
        return req.future

    def predict(self, X, timeout: float | None = 30.0) -> np.ndarray:
        """Synchronous convenience: submit + wait for class labels /
        predictions."""
        return self.submit(X, mode="predict").result(timeout)

    def predict_proba(self, X, timeout: float | None = 30.0) -> np.ndarray:
        """Synchronous convenience: submit + wait for probabilities
        (classification executors only)."""
        if self._task != "classification":
            raise AttributeError(
                "predict_proba is classification-only; this batcher "
                "serves a regression executor"
            )
        return self.submit(X, mode="aggregate").result(timeout)

    def _serve_direct(self, req: _Request) -> Future:
        """The idle fast path: run the forward on the caller's thread,
        bypassing queue, worker, and future handoff. The occupancy slot
        was claimed by :meth:`submit`; released here in ``finally`` so
        a failed forward re-opens the path."""
        try:
            if not req.future.set_running_or_notify_cancel():
                return req.future
            t_claim = time.perf_counter()
            if telemetry.enabled():
                telemetry.inc_many((
                    ("sbt_serving_requests_total", 1.0),
                    ("sbt_serving_direct_dispatch_total", 1.0),
                ))
                if telemetry.arrival_events_wanted():
                    # the capturable arrival stream sees direct serves
                    # too — a replay replays them through the stepped
                    # coalescer, which is exactly the virtual-mode
                    # contract (composition is queue-order, not path)
                    bucket = None
                    if self._bucket_bounds is not None:
                        bucket = bucket_for(req.n, *self._bucket_bounds)
                    telemetry.emit_event({
                        "kind": "serving_request",
                        "rows": req.n,
                        "width": self._n_features,
                        "dtype": str(req.X.dtype),
                        "bucket": bucket,
                        "queue_depth": 0,
                        "trace_id": (req.trace.trace_id if req.trace
                                     else None),
                        "t_mono": time.monotonic(),
                    })
            ex = None
            t_fwd = 0.0
            try:
                if faults.ACTIVE is not None and req.poisoned:
                    # a poisoned direct serve fails alone by
                    # construction — there is no batch to protect
                    raise faults.PoisonedRequest(
                        "poisoned request (direct dispatch)"
                    )
                ex = self._resolve()
                # the same TRANSIENT-retry contract as the coalesced
                # path (bisect is vacuous for a lone request): direct
                # dispatch is the path that serves most low-concurrency
                # traffic, so `retries=` must apply here too. t_fwd
                # accumulates across attempts — retries are real
                # forward latency
                attempt = 0
                while True:
                    try:
                        if telemetry.sinks_active():
                            # someone is consuming events (open
                            # capture, armed recorder): full span
                            # treatment, trace installed so
                            # serving_direct/serving_forward carry
                            # the ids
                            with tracing.use(req.trace):
                                with telemetry.span("serving_direct",
                                                    rows=req.n):
                                    t0 = time.perf_counter()
                                    try:
                                        out = ex.forward(req.X)
                                    finally:
                                        t_fwd += (time.perf_counter()
                                                  - t0)
                        else:
                            # lean inline serve: metrics still count
                            # (inside the executor), spans are skipped
                            # — span events with no sink are built
                            # only to be dropped, and that build was a
                            # measurable slice of the per-request
                            # budget at concurrency 1
                            t0 = time.perf_counter()
                            try:
                                if hasattr(ex, "_forward_packed"):
                                    # submit() already validated: skip
                                    # the executor's re-validation pass
                                    (out,) = ex._forward_packed([req.X])
                                else:
                                    out = ex.forward(req.X)
                            finally:
                                t_fwd += time.perf_counter() - t0
                        break
                    except BaseException as e:  # noqa: BLE001 — retry ladder
                        if getattr(e, "transient", False) \
                                and attempt < self._retries:
                            attempt += 1
                            telemetry.inc("sbt_serving_retries_total")
                            telemetry.emit_event({
                                "kind": "serving_retry",
                                "attempt": attempt,
                                "requests": 1,
                                "error": repr(e),
                            })
                            if self._retry_backoff_s > 0:
                                time.sleep(self._retry_backoff_s
                                           * (2 ** (attempt - 1)))
                            continue
                        raise
                if not telemetry.sinks_active():
                    if req.trace is not None and hasattr(
                            ex, "min_bucket_rows"):
                        # no context was installed, so the executor's
                        # bucket annotations had nowhere to land —
                        # recompute the (deterministic) plan for the
                        # breakdown contract, from the RESOLVED
                        # executor's bounds (a swap may have changed
                        # them since this batcher snapshotted its own)
                        req.trace.annotations["bucket"] = list(
                            pack_plan(req.n, ex.min_bucket_rows,
                                      ex.max_batch_rows)
                        )
            except BaseException as e:  # noqa: BLE001 — delivered via the future
                self._finish_breakdown(
                    req, ex, t_claim, time.perf_counter(), t_fwd,
                    None, 1, error=repr(e), path="direct",
                )
                req.future.set_exception(e)
                telemetry.inc("sbt_serving_request_failures_total")
                telemetry.inc("sbt_serving_batch_errors_total")
                telemetry.emit_event({
                    "kind": "serving_batch_error",
                    "error": repr(e),
                    "requests": 1,
                    "rows": req.n,
                    "path": "direct",
                    "trace_id": (req.trace.trace_id if req.trace
                                 else None),
                    # same resolvability contract as the batch-path
                    # event: flight dumps index incidents by links
                    "links": ([req.trace.trace_id] if req.trace
                              else []),
                })
                return req.future
            t_done = time.perf_counter()
            piece = out
            try:
                if req.mode == "predict" and ex.task == "classification":
                    piece = ex.classes_[piece.argmax(axis=1)]
                self._finish_breakdown(req, ex, t_claim, t_done, t_fwd,
                                       None, 1, path="direct")
                req.future.set_result(piece)
            except BaseException as e:  # noqa: BLE001
                if not req.future.done():
                    req.future.set_exception(e)
            if telemetry.enabled():
                lat = t_done - req.t_submit
                telemetry.observe(
                    "sbt_serving_latency_seconds", lat,
                    exemplar=(req.trace.trace_id if req.trace else None),
                )
                telemetry.observe("sbt_serving_latency_seconds", lat,
                                  labels={"path": "direct"})
            return req.future
        finally:
            with self._occ_lock:
                self._occupancy -= 1
                # last-batch stamp doubles as the direct path's
                # liveness heartbeat for /healthz staleness math
                self._t_last_batch = time.monotonic()

    # -- observability -------------------------------------------------

    # a full queue that has not drained a batch for this long means
    # traffic is arriving but nothing is served (hung device forward);
    # an empty queue with an old last-batch age is just an idle process
    STALL_S = 10.0

    def health(self) -> dict:
        """Liveness facts for ``/healthz`` (registered automatically):
        healthy means SERVING traffic — closed, dead-worker (a crash
        the supervisor could not absorb), degraded (crash-loop reject
        mode), and stalled (queue pinned at its bound past
        :data:`STALL_S` with no batch completing) batchers all report
        unhealthy so a load balancer stops routing here."""
        depth = self._q.qsize()
        with self._sup_lock:
            worker = self._worker
            degraded = self._degraded
            crashes = len(self._crash_ts)
        # stepped mode has no worker by design: liveness there is just
        # "not closed" (the owner serves on its own thread)
        alive = (worker.is_alive() if worker is not None
                 else not self._closed)
        age = time.monotonic() - self._t_last_batch
        stalled = depth >= self._q.maxsize and age > self.STALL_S
        return {
            "healthy": (not self._closed and alive and not stalled
                        and not degraded),
            "closed": self._closed,
            "worker_alive": alive,
            "degraded": degraded,
            "crashes_in_window": crashes,
            "stalled": stalled,
            "queue_depth": depth,
            "max_queue": self._q.maxsize,
            "last_batch_age_s": age,
        }

    def stats(self) -> dict:
        """Serving stats off the live registry: cumulative counters
        (including the direct-vs-coalesced dispatch split) plus
        request-latency quantiles (p50/p95/p99, log-bucket
        interpolation — the same numbers ``/varz`` serves)."""
        reg = telemetry.registry()
        return {
            "requests": reg.counter("sbt_serving_requests_total").value,
            "batches": reg.counter("sbt_serving_batches_total").value,
            "direct": reg.counter(
                "sbt_serving_direct_dispatch_total").value,
            "coalesced": reg.counter("sbt_serving_coalesced_total").value,
            "overloaded": reg.counter("sbt_serving_overloaded_total").value,
            "batch_errors": reg.counter(
                "sbt_serving_batch_errors_total").value,
            "retries": reg.counter("sbt_serving_retries_total").value,
            "shed": {
                reason: reg.counter("sbt_serving_shed_total",
                                    labels={"reason": reason}).value
                for reason in ("overload", "deadline", "degraded")
            },
            "worker_crashes": reg.counter(
                "sbt_serving_worker_crashes_total").value,
            "latency": reg.histogram(
                "sbt_serving_latency_seconds").quantiles(),
            "latency_direct": reg.histogram(
                "sbt_serving_latency_seconds",
                labels={"path": "direct"}).quantiles(),
            "latency_coalesced": reg.histogram(
                "sbt_serving_latency_seconds",
                labels={"path": "coalesced"}).quantiles(),
            **self.health(),
        }

    # -- lifecycle -----------------------------------------------------

    def close(self, timeout: float = 10.0) -> None:
        """Stop accepting requests, let the in-flight batch finish,
        fail whatever is still queued, join the worker."""
        # the flag flip is a check-then-act: two racing close() calls
        # must not BOTH run the drain loop below (found by the
        # shared-state-unlocked lint rule when this class was marked)
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        # stop BEFORE the join: the worker's outer get() polls the flag
        # every 100ms, so even with a full queue (sentinel un-enqueueable)
        # it exits after at most the in-flight batch + one poll — the
        # join never has to burn its whole timeout on a set-too-late flag
        self._stop.set()
        try:  # best-effort wake so an idle worker exits immediately
            self._q.put_nowait(_SHUTDOWN)
        except Full:
            pass
        with self._sup_lock:
            # the supervisor may have replaced the worker thread since
            # construction: join the CURRENT one
            worker = self._worker
        if worker is not None:
            worker.join(timeout)
        # anything still queued was never forwarded — fail it loudly
        while True:
            try:
                req = self._q.get_nowait()
            except Empty:
                break
            if req is _SHUTDOWN:
                continue
            if req.future.set_running_or_notify_cancel():
                req.future.set_exception(
                    RuntimeError("MicroBatcher closed before this "
                                 "request was served")
                )

    def retire(self) -> None:
        """Close AND leave ``/healthz``. ``close()`` alone keeps this
        batcher in the health set reporting unhealthy (the
        load-balancer drain signal); retire() is for rolling over to a
        new batcher in the same process, where the old one's 503 would
        poison an otherwise healthy node."""
        self.close()
        from spark_bagging_tpu.telemetry import server as telemetry_server

        telemetry_server.remove_health_source(self._health_handle)

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- stepped mode (deterministic replay) ---------------------------

    def run_pending(self, max_batches: int | None = None) -> int:
        """Serve everything queued, synchronously, on THIS thread.

        Stepped-mode (``threaded=False``) counterpart of the worker
        loop: drains the queue into batches by the same row rule
        (gather until ``max_batch_rows``; one request may overshoot,
        exactly like the worker) and runs each through
        :meth:`_run_batch` — real padding, real tracing, real
        telemetry. What it deliberately does NOT have is the worker's
        clock: batch composition is a pure function of the submission
        order, which is what makes ``same capture + same seed ⇒
        identical batches, bitwise-identical outputs`` a contract the
        replay harness can assert rather than hope for. Returns the
        number of batches served.
        """
        if self._worker is not None:
            raise RuntimeError(
                "run_pending() is stepped-mode only; this batcher "
                "runs a worker thread (construct with threaded=False)"
            )
        if self._closed:
            raise RuntimeError("MicroBatcher is closed")
        ran = 0
        while max_batches is None or ran < max_batches:
            batch: list = []
            rows = 0
            while rows < self.max_batch_rows:
                try:
                    req = self._q.get_nowait()
                except Empty:
                    break
                if req is _SHUTDOWN:
                    continue
                batch.append(req)
                rows += req.n
            if not batch:
                break
            self._run_batch(batch)
            ran += 1
        return ran

    # -- worker side ---------------------------------------------------

    def _worker_main(self) -> None:
        """Thread target: the coalescing loop under supervision. A
        crash that escapes the per-batch guard lands in
        :meth:`_on_worker_crash` instead of silently killing serving."""
        try:
            self._loop()
        # sbt-lint: disable=swallowed-fault — the fault IS the payload: the supervisor counts, flight-records, and restarts/degrades on it
        except BaseException as e:  # noqa: BLE001 — the supervision seam
            self._on_worker_crash(e)

    def _on_worker_crash(self, e: BaseException) -> None:
        """Supervisor: count + record the crash, then either restart a
        fresh worker or — on a crash loop — trip degraded reject mode
        (one flight dump, /healthz 503, queue drained with
        :class:`Degraded`)."""
        telemetry.inc("sbt_serving_worker_crashes_total")
        telemetry.emit_event({
            "kind": "serving_worker_crash", "error": repr(e),
        })
        restart = False
        with self._sup_lock:
            now = time.monotonic()
            self._crash_ts.append(now)
            looping = (
                len(self._crash_ts) == self._crash_ts.maxlen
                and now - self._crash_ts[0] <= self._crash_window_s
            )
            if self._closed or not self._supervise:
                return
            if looping:
                self._degraded = True
            else:
                restart = True
        if not restart:
            telemetry.inc("sbt_serving_crash_loops_total")
            # serving_crash_loop is a flight-recorder TRIGGER: exactly
            # one dump for the incident (per-kind cooldown), with the
            # crash events of the loop in its ring
            telemetry.emit_event({
                "kind": "serving_crash_loop",
                "crashes": len(self._crash_ts),
                "window_s": self._crash_window_s,
                "error": repr(e),
            })
            self._fail_queued(Degraded(
                "batcher entered degraded reject mode (worker crash "
                "loop)"
            ), reason="degraded")
            return
        telemetry.inc("sbt_serving_worker_restarts_total")
        t = threading.Thread(target=self._worker_main, daemon=True,
                             name="serving-batcher")
        with self._sup_lock:
            self._worker = t
        t.start()

    def _fail_queued(self, exc: BaseException, reason: str) -> None:
        """Drain the queue, failing every still-pending request with
        ``exc`` (counted as shed under ``reason``) — degraded mode
        must reject, not strand."""
        while True:
            try:
                req = self._q.get_nowait()
            except Empty:
                return
            if req is _SHUTDOWN:
                continue
            if req.future.set_running_or_notify_cancel():
                telemetry.inc("sbt_serving_shed_total",
                              labels={"reason": reason})
                req.future.set_exception(exc)

    def revive(self) -> None:
        """Operator reset out of degraded reject mode: clear the crash
        history and start a fresh worker. A no-op on a healthy
        threaded batcher; raises after :meth:`close`."""
        if self._closed:
            raise RuntimeError("MicroBatcher is closed")
        t: threading.Thread | None = None
        with self._sup_lock:
            self._degraded = False
            self._crash_ts.clear()
            alive = self._worker is not None and self._worker.is_alive()
            if not alive and self._threaded:
                t = threading.Thread(target=self._worker_main,
                                     daemon=True,
                                     name="serving-batcher")
                self._worker = t
        if t is not None:
            telemetry.inc("sbt_serving_worker_restarts_total")
            t.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.1)
            except Empty:
                continue
            if first is _SHUTDOWN:
                return
            if faults.ACTIVE is not None:
                # worker-crash drills: the probe sits AFTER a request
                # is claimed (deterministic per-claim hit counts); its
                # future is failed before the crash propagates so no
                # caller hangs on a request the dying worker took
                try:
                    faults.fire("batcher.worker")
                except BaseException:
                    if first.future.set_running_or_notify_cancel():
                        first.future.set_exception(RuntimeError(
                            "serving worker crashed (injected fault)"
                        ))
                    raise
            batch = [first]
            rows = first.n
            deadline = time.perf_counter() + self.max_delay_s
            while rows < self.max_batch_rows:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    # linger at most idle_flush on an empty queue: an
                    # Empty here means the wave is absorbed — launch
                    # now instead of sleeping out the window
                    req = self._q.get(
                        timeout=min(remaining, self.idle_flush_s)
                    )
                except Empty:
                    break
                if req is _SHUTDOWN:
                    self._stop.set()
                    break
                batch.append(req)
                rows += req.n
            self._run_batch(batch)

    #: consecutive one-request coalesced batches before the adaptive
    #: dispatcher concludes there is nobody to coalesce with and
    #: serves submits inline (direct mode); any multi-request batch or
    #: contended submit resets the streak and the mode
    DIRECT_AFTER_SINGLETONS = 8

    def _run_batch(self, batch: list) -> None:
        # in-queue deadline expiry happens at claim time, BEFORE the
        # futures are claimed for serving: an expired request is shed
        # as DeadlineExceeded (reason="deadline"), never served late
        # and never billed as Overloaded
        if any(r.deadline_t is not None for r in batch):
            batch = self._expire_deadlines(batch)
        # claim the futures; drop requests cancelled while queued
        live = [r for r in batch if r.future.set_running_or_notify_cancel()]
        if not live:
            return
        if self._direct:
            # the adaptive-dispatch evidence loop: singleton batches
            # mean the delay window buys nothing — after a streak of
            # them, demote to inline serving; one coalesced batch
            # proves concurrency and revokes it. The batch also HOLDS
            # an occupancy slot while it forwards (released in
            # _release_slot): without it, a submit landing while
            # the worker is mid-forward on an empty queue would see
            # "nothing in flight" and serve inline CONCURRENTLY with
            # the worker — and direct mode could survive real
            # concurrency-2 traffic because the revocation signal
            # (occupancy > 0) never fired
            with self._occ_lock:
                self._occupancy += 1
                if len(live) == 1:
                    self._singleton_streak += 1
                    if (self._singleton_streak
                            >= self.DIRECT_AFTER_SINGLETONS):
                        self._mode_direct = True
                else:
                    self._singleton_streak = 0
                    self._mode_direct = False
            token = [True]
        else:
            token = []
        try:
            self._run_batch_held(live, token)
        except BaseException as e:  # noqa: BLE001 — deliver, then crash
            # a crash that escaped even _run_batch_held's guards (a
            # sink dying in the scatter span, an injected fault): the
            # futures this batch CLAIMED must fail before the crash
            # reaches the supervisor — a restarted worker never
            # revisits them, and a stranded claimed future blocks its
            # caller forever with /healthz reporting healthy
            for r in live:
                if not r.future.done():
                    r.future.set_exception(RuntimeError(
                        f"serving worker crashed mid-batch: {e!r}"
                    ))
            raise
        finally:
            self._release_slot(token)  # backstop; normally a no-op

    def _release_slot(self, token: list) -> None:
        """Release a batch's occupancy slot exactly once. Called right
        after the FORWARD completes — before futures resolve — because
        a closed-loop client wakes on its future and submits again
        immediately: if the slot outlived the scatter, that submit
        would read occupancy 1 and revoke direct mode the moment it
        was earned. The slot's job is only to cover the device
        forward (no inline serve may run concurrently with it)."""
        if token:
            token.clear()
            with self._occ_lock:
                self._occupancy -= 1

    def _expire_deadlines(self, batch: list) -> list:
        """Shed every claimed request whose deadline already passed on
        the batcher's clock; returns the survivors."""
        now = self._clock()
        kept: list = []
        for r in batch:
            if r.deadline_t is None or now <= r.deadline_t:
                kept.append(r)
                continue
            if not r.future.set_running_or_notify_cancel():
                continue  # cancelled while queued: nothing to shed
            telemetry.inc("sbt_serving_shed_total",
                          labels={"reason": "deadline"})
            telemetry.emit_event({
                "kind": "serving_deadline_exceeded",
                "rows": r.n,
                "late_s": now - r.deadline_t,
                "trace_id": (r.trace.trace_id if r.trace else None),
            })
            if r.trace is not None:
                r.trace.breakdown.update({
                    "error": "DeadlineExceeded", "path": "shed",
                })
            r.future.set_exception(DeadlineExceeded(
                "request expired in queue (deadline passed by "
                f"{(now - r.deadline_t) * 1e3:.1f} ms)"
            ))
        return kept

    def _forward_once(self, ex: Any, reqs: list) -> list:
        """ONE forward attempt over ``reqs``; returns one output per
        request. The chaos probe and the poison check sit here, so
        retries and bisection re-drive them deterministically."""
        if faults.ACTIVE is not None:
            faults.fire("batcher.batch_forward", requests=len(reqs))
            if any(r.poisoned for r in reqs):
                raise faults.PoisonedRequest(
                    f"poisoned request in batch of {len(reqs)}"
                )
        rows = sum(r.n for r in reqs)
        with telemetry.span("serving_batch", rows=rows,
                            requests=len(reqs)):
            if hasattr(ex, "forward_parts"):
                # ragged packing: request blocks scatter straight into
                # the pack plan's slabs (one copy per row, minimal
                # padding) and come back pre-split per request
                return list(ex.forward_parts([r.X for r in reqs]))
            # plain-callable executors (no ragged seam): concatenate
            # and slice, as ever
            X = (reqs[0].X if len(reqs) == 1
                 else np.concatenate([r.X for r in reqs]))
            out = ex.forward(X)
            outs = []
            off = 0
            for r in reqs:
                outs.append(out[off:off + r.n])
                off += r.n
            return outs

    def _serve_requests(self, ex: Any, reqs: list) -> list:
        """Serve ``reqs`` with the recovery ladder: bounded retry with
        exponential backoff for TRANSIENT failures, then bisection so
        a poisoned request fails alone. Returns one output per request
        — a :class:`_Failed` sentinel where that request's forward
        ultimately failed (delivered per-future by the scatter)."""
        attempt = 0
        while True:
            try:
                return self._forward_once(ex, reqs)
            except BaseException as e:  # noqa: BLE001 — recovery ladder
                if getattr(e, "transient", False) \
                        and attempt < self._retries:
                    attempt += 1
                    telemetry.inc("sbt_serving_retries_total")
                    telemetry.emit_event({
                        "kind": "serving_retry",
                        "attempt": attempt,
                        "requests": len(reqs),
                        "error": repr(e),
                    })
                    if self._retry_backoff_s > 0:
                        time.sleep(
                            self._retry_backoff_s * (2 ** (attempt - 1))
                        )
                    continue
                if len(reqs) > 1 and self._bisect:
                    # bisect-on-poison: each half serves (and retries)
                    # independently; recursion bottoms out at single
                    # requests, so exactly the bad ones fail
                    telemetry.inc("sbt_serving_batch_bisects_total")
                    mid = (len(reqs) + 1) // 2
                    return (self._serve_requests(ex, reqs[:mid])
                            + self._serve_requests(ex, reqs[mid:]))
                telemetry.inc("sbt_serving_request_failures_total",
                              float(len(reqs)))
                telemetry.inc("sbt_serving_batch_errors_total")
                telemetry.emit_event({
                    "kind": "serving_batch_error",
                    "error": repr(e),
                    "requests": len(reqs),
                    "rows": sum(r.n for r in reqs),
                    "links": [r.trace.trace_id for r in reqs
                              if r.trace is not None],
                })
                return [_Failed(e)] * len(reqs)

    def _run_batch_held(self, live: list, token: list) -> None:
        t_claim = time.perf_counter()
        if telemetry.enabled():
            telemetry.inc("sbt_serving_batches_total")
            telemetry.inc("sbt_serving_coalesced_total",
                          float(len(live)))
            telemetry.set_gauge("sbt_serving_queue_depth",
                                self._q.qsize())
        # one batch-level trace context linked to every member request:
        # the coalesced batch/forward/scatter spans resolve from any of
        # the trace ids riding the batch
        traced = [r.trace for r in live if r.trace is not None]
        bctx = tracing.batch_context(traced) if traced else None
        ex = None
        t_fwd = 0.0
        try:
            ex = self._resolve()
            with tracing.use(bctx):
                t0 = time.perf_counter()
                try:
                    # recovery lives INSIDE the timed window: retries
                    # and bisection are real forward latency the
                    # breakdown must attribute honestly
                    outs = self._serve_requests(ex, live)
                finally:
                    t_fwd = time.perf_counter() - t0
        except BaseException as e:  # noqa: BLE001 — delivered per-future
            # catastrophic path (executor resolution failed, or
            # recovery itself died): release BEFORE delivering — a
            # client waking on the exception may submit immediately
            self._release_slot(token)
            t_fail = time.perf_counter()
            for r in live:
                self._finish_breakdown(
                    r, ex, t_claim, t_fail, t_fwd, bctx, len(live),
                    error=repr(e),
                )
                r.future.set_exception(e)
            telemetry.inc("sbt_serving_batch_errors_total")
            telemetry.emit_event({
                "kind": "serving_batch_error",
                "error": repr(e),
                "requests": len(live),
                "rows": sum(r.n for r in live),
                "trace_id": bctx.trace_id if bctx else None,
                "links": [t.trace_id for t in traced],
            })
            return
        # the device forward is done: drop the occupancy slot BEFORE
        # any future resolves (see _release_slot)
        self._release_slot(token)
        # sbt-lint: disable=shared-state-unlocked — last-write-wins monotonic stamp (worker thread + direct finishers); /healthz readers tolerate a stale float
        self._t_last_batch = time.monotonic()
        with tracing.use(bctx):
            with telemetry.span("serving_scatter", requests=len(live)):
                t_done = time.perf_counter()
                for i, r in enumerate(live):
                    piece = outs[i]
                    if isinstance(piece, _Failed):
                        # this request's forward failed after the full
                        # recovery ladder — it fails ALONE; its
                        # batch-mates resolve normally below
                        self._finish_breakdown(
                            r, ex, t_claim, t_done, t_fwd, bctx,
                            len(live), error=repr(piece.error),
                        )
                        r.future.set_exception(piece.error)
                        continue
                    try:
                        if (r.mode == "predict"
                                and ex.task == "classification"):
                            piece = ex.classes_[piece.argmax(axis=1)]
                        self._finish_breakdown(
                            r, ex, t_claim, t_done, t_fwd, bctx,
                            len(live),
                        )
                        r.future.set_result(piece)
                    except BaseException as e:  # noqa: BLE001
                        if not r.future.done():
                            r.future.set_exception(e)
                    if telemetry.enabled():
                        lat = t_done - r.t_submit
                        telemetry.observe(
                            "sbt_serving_latency_seconds", lat,
                            exemplar=(r.trace.trace_id if r.trace
                                      else None),
                        )
                        telemetry.observe(
                            "sbt_serving_latency_seconds", lat,
                            labels={"path": "coalesced"},
                        )

    @staticmethod
    def _finish_breakdown(
        r: _Request, ex: Any, t_claim: float, t_done: float,
        t_fwd: float, bctx: "tracing.TraceContext | None",
        n_requests: int, error: str | None = None,
        path: str = "coalesced",
    ) -> None:
        """Fill the request trace's timing breakdown — complete before
        the future resolves, so `future.result(); future.trace.breakdown`
        never races."""
        if r.trace is None:
            return
        # bucket annotations land on the batch context when one exists
        # (coalesced path); direct serves annotate the request trace
        src = bctx if bctx is not None else r.trace
        buckets = src.annotations.get("bucket", []) if src else []
        bd = {
            "queue_ms": (t_claim - r.t_submit) * 1e3,
            "batch_ms": (t_done - t_claim) * 1e3,
            "forward_ms": t_fwd * 1e3,
            "total_ms": (t_done - r.t_submit) * 1e3,
            "batch_size": n_requests,
            "path": path,
            "bucket": (buckets[0] if len(buckets) == 1
                       else list(buckets) or None),
            "model_name": getattr(ex, "model_name", None),
            "model_version": getattr(ex, "model_version", None),
            "batch_trace_id": bctx.trace_id if bctx else None,
        }
        if error is not None:
            bd["error"] = error
        j = r.trace.journey
        if j is not None:
            # tenancy journey: the fleet minted this trace before
            # admission, so re-anchor the decomposition at the fleet
            # boundary. An AOT restore the request absorbed is carved
            # OUT of its host interval — queue wait for a stepped
            # restore (touch runs between submit and run_pending),
            # dispatch for a threaded one (touch runs before submit)
            # — and surfaced as its own stage, keeping the tiling
            # exact: admission + wfq + dispatch + restore + queue +
            # batch == total (re-based to the fleet submit instant).
            pre = float(j.get("restore_pre_ms", 0.0))
            post = float(j.get("restore_post_ms", 0.0))
            bd["queue_ms"] = bd["queue_ms"] - post
            bd["tenant"] = j.get("tenant")
            bd["admission_ms"] = j.get("admission_ms", 0.0)
            bd["wfq_ms"] = j.get("wfq_ms", 0.0)
            bd["restore_ms"] = pre + post
            bd["dispatch_ms"] = (
                (r.t_submit - j["t_pop"]) * 1e3 - pre
                if "t_pop" in j else 0.0)
            if "t0" in j:
                bd["total_ms"] = (t_done - j["t0"]) * 1e3
        r.trace.breakdown.update(bd)
        # performance-attribution probe (telemetry/perf.py): rides the
        # breakdown that was just built — one module-attribute read
        # when no plane is installed, and no probe at all on the bare
        # hot path (trace None returned above)
        ap = _perf.ACTIVE
        if ap is not None:
            ap.observe_breakdown(bd, trace_id=r.trace.trace_id)
