"""Unified compiled-program cache — one program, compiled once, reused
everywhere.

Before this module, three producers each compiled (and cached) the SAME
forward independently: the batch-predict jits in ``bagging.py`` (jit
dispatch cache, keyed by input shape), the serving executor's
per-bucket AOT compiles (``serving/executor.py``, per-instance dict),
and the persisted executable cache (``serving/aot_cache.py``). Two
executors for the same fitted model — or a batch ``predict_proba``
call at a row count the serving ladder already compiled — paid the XLA
compile again. This module is the one table they all share: a
process-wide map from a :class:`ProgramKey` to a compiled executable,
so a program compiled ANYWHERE (executor warmup, a batch predict, an
AOT restore) is a cache hit everywhere else.

Key contract (why each component is in the key):

- ``fingerprint`` — sha256 of the fitted params/subspaces pytree plus
  estimator class, task, feature width and class set
  (:func:`fingerprint_params`): two models that would compile
  different programs must never share an entry;
- ``variant`` — which closure over those params this program traces
  (aggregated vs per-replica forward, voting mode, replica chunking,
  identity-subspace fast path): same weights, different computation;
- ``bucket`` — the row count the program was lowered for (XLA compiles
  per shape);
- ``mesh`` — the ``(data, replica)`` device grid the program was
  partitioned over (``None`` = single-device): a single-device
  executable is the WRONG program for a mesh executor and vice versa;
- ``donate`` — donation changes the program's buffer aliasing;
- ``jax_version`` / ``backend`` / ``device_kind`` — an executable is
  only meaningful on the toolchain + hardware kind that built it.

The cache is bounded (LRU eviction at ``capacity`` entries) and
thread-safe; lookups/inserts count ``sbt_program_cache_*`` telemetry.
Entries hold compiled executables only — parameters are passed at call
time, so a cache entry pins no model weights.
"""

from __future__ import annotations

import hashlib
import time
from collections import OrderedDict
from typing import Any, Callable, NamedTuple

from spark_bagging_tpu import faults, telemetry
from spark_bagging_tpu.analysis.locks import make_lock
from spark_bagging_tpu.telemetry import capacity as _capacity


class ProgramKey(NamedTuple):
    """Identity of one compiled forward — see the module docstring."""

    fingerprint: str
    variant: str
    bucket: int
    mesh: tuple[int, int] | None
    donate: bool
    jax_version: str
    backend: str
    device_kind: str


def toolchain_id() -> tuple[str, str, str]:
    """``(jax_version, backend, device_kind)`` for this process — the
    shared tail of every :class:`ProgramKey` and of the AOT disk-cache
    key (``serving/aot_cache.py``)."""
    import jax

    devices = jax.devices()
    kind = devices[0].device_kind if devices else "unknown"
    return jax.__version__, jax.default_backend(), str(kind)


def fingerprint_params(model_cls: type, task: str, n_features: int,
                       classes, params: Any, subspaces: Any) -> str:
    """sha256 identity of the program a forward over ``params`` would
    compile: leaf bytes + shapes + dtypes + tree structure, plus the
    estimator class, task, feature width, and class set."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    h.update(
        f"{model_cls.__module__}:{model_cls.__qualname__}|{task}|"
        f"{n_features}\n".encode()
    )
    if classes is not None:
        c = np.asarray(classes)
        h.update(str(c.dtype).encode())
        h.update(c.tobytes())
    leaves, treedef = jax.tree_util.tree_flatten((params, subspaces))
    h.update(str(treedef).encode())
    for leaf in leaves:
        a = np.asarray(leaf)
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def fingerprint_model(model: Any) -> str:
    """:func:`fingerprint_params` for a fitted estimator (cached on the
    instance, invalidated when a refit rebinds ``ensemble_`` — the
    hash walks every parameter byte, which must not be paid per
    ``predict`` call)."""
    token = getattr(model, "_fp_token", None)
    if token is not None and token[0] is model.ensemble_:
        return token[1]
    fp = fingerprint_params(
        type(model), model.task, int(model.n_features_in_),
        getattr(model, "classes_", None), model.ensemble_,
        model.subspaces_,
    )
    try:
        model._fp_token = (model.ensemble_, fp)
    except AttributeError:
        pass  # slotted/frozen estimators just recompute
    return fp


def forward_variant(model: Any, kind: str = "aggregated") -> str:
    """The static-closure-config component of a :class:`ProgramKey`:
    everything besides the weights that changes what the forward
    traces. ``kind`` distinguishes the aggregated serving program from
    the per-replica (disagreement-tap / uncertainty) twin."""
    return (
        f"{kind}|voting={getattr(model, 'voting', None)}"
        f"|chunk={model._eff_chunk() if hasattr(model, '_eff_chunk') else None}"
        f"|ident={getattr(model, '_identity_subspace', None)}"
    )


def mesh_shape(mesh: Any) -> tuple[int, int] | None:
    """Normalize a Mesh (or None) to the ``(data, replica)`` tuple the
    key stores — mesh OBJECTS differ per process; their shape is the
    portable identity."""
    if mesh is None:
        return None
    from spark_bagging_tpu.parallel.mesh import DATA_AXIS, REPLICA_AXIS

    return (int(mesh.shape.get(DATA_AXIS, 1)),
            int(mesh.shape.get(REPLICA_AXIS, 1)))


class _Entry:
    """One resident program: the executable plus the residency facts
    the capacity plane's explainer reads (bytes + measurement source,
    hit counts, a monotonic insert/hit sequence — the workload-pure
    event clock the churn drill's transcript records — and wall-clock
    timestamps for live last-hit-age reporting only, never digests)."""

    __slots__ = ("compiled", "nbytes", "source", "hits",
                 "seq_inserted", "seq_last_hit", "ts_inserted",
                 "ts_last_hit")

    def __init__(self, compiled: Any, nbytes: int | None, source: str,
                 seq: int):
        self.compiled = compiled
        self.nbytes = nbytes
        self.source = source
        self.hits = 0
        self.seq_inserted = seq
        self.seq_last_hit = seq
        self.ts_inserted = time.time()
        self.ts_last_hit: float | None = None


# sbt-lint: shared-state
class ProgramCache:
    """Bounded, thread-safe LRU map ``ProgramKey -> compiled``.

    Since ISSUE 16 each entry carries residency metadata (measured
    executable bytes via :func:`telemetry.capacity.executable_bytes`,
    hit counts, insert sequence) and lookups/evictions feed the armed
    capacity plane: hit/miss/eviction counters gain ``model=`` owner
    labels (resolved lazily through the plane's fingerprint map, so
    only COMMITTED owners ever appear) while the unlabeled totals keep
    their exact pre-existing meaning for dashboard continuity.
    """

    def __init__(self, capacity: int = 256,
                 pin_policy: Callable[[str], bool] | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        #: opt-in demand-aware victim selection [ISSUE 17]: a
        #: fingerprint predicate (e.g. ``tenancy.residency.
        #: cache_pin_policy``) whose True entries are skipped in LRU
        #: eviction order. None (default) keeps the strict-LRU
        #: behavior every committed churn baseline was recorded under.
        self._pin_policy = pin_policy
        self._lock = make_lock("serving.program_cache")
        self._entries: OrderedDict[ProgramKey, _Entry] = OrderedDict()
        self._seq = 0

    def get(self, key: ProgramKey) -> Any | None:
        """The cached executable for ``key``, or None (counted as a
        hit/miss either way)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self._seq += 1
                entry.hits += 1
                entry.seq_last_hit = self._seq
                entry.ts_last_hit = time.time()
        name = ("sbt_program_cache_hits_total" if entry is not None
                else "sbt_program_cache_misses_total")
        telemetry.inc(name)
        cap = _capacity.ACTIVE
        if cap is not None:
            owner = cap.owner_label(key.fingerprint)
            if owner is not None:
                telemetry.inc(name, labels={"model": owner})
        return None if entry is None else entry.compiled

    def put(self, key: ProgramKey, compiled: Any) -> Any:
        """Insert-if-absent; returns the winning executable (the first
        insert wins, so racing builders converge on one program)."""
        if faults.ACTIVE is not None:
            # chaos probe: a failed insert surfaces to the compiling
            # caller (executor build, swap pre-compile) exactly where
            # an allocation failure would
            faults.fire("program_cache.put", bucket=key.bucket)
        # measure OUTSIDE the lock: the serialize fallback is not free,
        # and put() runs on the compile path where seconds were already
        # spent — never on the per-request path
        nbytes, source = _capacity.executable_bytes(compiled)
        evicted: list[tuple[ProgramKey, _Entry]] = []
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                self._entries.move_to_end(key)
                return existing.compiled
            self._seq += 1
            self._entries[key] = _Entry(compiled, nbytes, source,
                                        self._seq)
            pin_violations = 0
            while len(self._entries) > self.capacity:
                victim, violated = self._pick_victim_locked(key)
                pin_violations += int(violated)
                evicted.append((victim, self._entries.pop(victim)))
            size = len(self._entries)
            total_bytes = sum(e.nbytes or 0
                              for e in self._entries.values())
        if pin_violations:
            # the hot set alone overflows the cache: the pin policy
            # had to sacrifice a pinned entry — the capacity signal
            # that this cache is undersized for its fleet. Unlabeled
            # total first (the series alert rules sample), then the
            # locating twin.
            telemetry.inc("sbt_tenancy_pin_violations_total",
                          float(pin_violations))
            telemetry.inc("sbt_tenancy_pin_violations_total",
                          float(pin_violations),
                          labels={"level": "cache"})
        if evicted:
            telemetry.inc("sbt_program_cache_evictions_total",
                          float(len(evicted)))
            cap = _capacity.ACTIVE
            for ekey, entry in evicted:
                if cap is None:
                    continue
                owner = cap.observe_eviction(
                    fingerprint=ekey.fingerprint, bucket=ekey.bucket,
                    variant=ekey.variant, nbytes=entry.nbytes,
                    seq=entry.seq_inserted,
                )
                if owner != _capacity.UNATTRIBUTED:
                    telemetry.inc("sbt_program_cache_evictions_total",
                                  labels={"model": owner})
        telemetry.set_gauge("sbt_program_cache_entries", float(size))
        telemetry.set_gauge("sbt_program_cache_bytes",
                            float(total_bytes))
        return compiled

    def _pick_victim_locked(
            self, protect: ProgramKey) -> tuple[ProgramKey, bool]:
        """The next eviction victim (never ``protect``, the entry just
        inserted). Strict LRU head without a pin policy — the exact
        pre-ISSUE-17 behavior every committed churn baseline was
        recorded under. With one, the first UNPINNED key in LRU order;
        when everything is pinned the LRU head goes anyway, flagged
        (``True`` in the return) so the caller can count it."""
        if self._pin_policy is None:
            return next(iter(self._entries)), False
        fallback: ProgramKey | None = None
        for k in self._entries:
            if k == protect:
                continue
            if fallback is None:
                fallback = k
            if not self._pin_policy(k.fingerprint):
                return k, False
        if fallback is None:  # capacity 1 and only the fresh insert
            return protect, False
        return fallback, True

    def drop_fingerprint(self, fingerprint: str) -> int:
        """Remove every entry compiled from ``fingerprint`` — the
        tenant-demotion seam [ISSUE 17]: the residency manager calls
        this after releasing a demoted executor's in-instance
        programs, so a cold tenant's cache footprint goes to zero
        instead of aging out. Dropped entries are charged through the
        SAME counters + capacity-plane eviction seam as pressure
        evictions, keeping the ledger's attribution reconciled.
        Returns the number of entries dropped."""
        dropped: list[tuple[ProgramKey, _Entry]] = []
        with self._lock:
            keys = [k for k in self._entries
                    if k.fingerprint == fingerprint]
            for k in keys:
                dropped.append((k, self._entries.pop(k)))
            size = len(self._entries)
            total_bytes = sum(e.nbytes or 0
                              for e in self._entries.values())
        if not dropped:
            return 0
        telemetry.inc("sbt_program_cache_evictions_total",
                      float(len(dropped)))
        cap = _capacity.ACTIVE
        for ekey, entry in dropped:
            if cap is None:
                continue
            owner = cap.observe_eviction(
                fingerprint=ekey.fingerprint, bucket=ekey.bucket,
                variant=ekey.variant, nbytes=entry.nbytes,
                seq=entry.seq_inserted,
            )
            if owner != _capacity.UNATTRIBUTED:
                telemetry.inc("sbt_program_cache_evictions_total",
                              labels={"model": owner})
        telemetry.set_gauge("sbt_program_cache_entries", float(size))
        telemetry.set_gauge("sbt_program_cache_bytes",
                            float(total_bytes))
        return len(dropped)

    def get_or_build(self, key: ProgramKey,
                     build: Callable[[], Any]) -> tuple[Any, bool]:
        """``(compiled, was_hit)``. The build runs OUTSIDE the cache
        lock (an XLA compile can take seconds; holding the table lock
        would serialize unrelated models' compiles); racing same-key
        builders both compile and the first ``put`` wins."""
        compiled = self.get(key)
        if compiled is not None:
            return compiled, True
        return self.put(key, build()), False

    def clear(self) -> None:
        """Drop every entry (tests simulating a fresh process)."""
        with self._lock:
            self._entries.clear()
        telemetry.set_gauge("sbt_program_cache_entries", 0.0)
        telemetry.set_gauge("sbt_program_cache_bytes", 0.0)

    def stats(self) -> dict:
        with self._lock:
            nbytes = sum(e.nbytes or 0 for e in self._entries.values())
            unmeasured = sum(1 for e in self._entries.values()
                             if e.nbytes is None)
            return {"entries": len(self._entries),
                    "capacity": self.capacity,
                    "bytes": nbytes,
                    "unmeasured": unmeasured}

    def snapshot(self) -> dict:
        """Residency raw material for the capacity plane's ledger and
        explainer: every entry LRU-first (position 0 is next to evict)
        with its key fields and metadata, plus the totals the ledger
        reconciles against. Point-in-time consistent: one lock hold."""
        with self._lock:
            entries = []
            for pos, (key, e) in enumerate(self._entries.items()):
                entries.append({
                    "lru_position": pos,
                    "fingerprint": key.fingerprint,
                    "variant": key.variant,
                    "bucket": key.bucket,
                    "mesh": key.mesh,
                    "bytes": e.nbytes,
                    "source": e.source,
                    "hits": e.hits,
                    "seq_inserted": e.seq_inserted,
                    "seq_last_hit": e.seq_last_hit,
                    "ts_last_hit": e.ts_last_hit,
                })
            return {
                "capacity": self.capacity,
                "entries_total": len(entries),
                "bytes_total": sum(e["bytes"] or 0 for e in entries),
                "unmeasured_total": sum(1 for e in entries
                                        if e["bytes"] is None),
                "entries": entries,
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


_default: ProgramCache | None = None
_default_lock = make_lock("serving.program_cache.default")


def cache() -> ProgramCache:
    """The process-wide cache every producer shares."""
    global _default
    with _default_lock:
        if _default is None:
            _default = ProgramCache()
        return _default


def install(c: ProgramCache | None) -> ProgramCache | None:
    """Swap the process-wide cache, returning the previous one — the
    churn drill's save/restore seam (mirrors ``telemetry.perf`` /
    ``telemetry.capacity``). ``None`` restores lazy re-creation."""
    global _default
    with _default_lock:
        prev = _default
        _default = c
    return prev


def clear() -> None:
    """Reset the process-wide cache (tests; a no-op if never used)."""
    with _default_lock:
        if _default is not None:
            _default.clear()
