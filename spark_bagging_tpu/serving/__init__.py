"""Online inference serving: micro-batched, shape-bucketed, hot-swappable.

The training stack ends at batch ``predict``/``predict_proba`` — per
call, a request pays Python dispatch, a fresh h2d transfer, and (for a
novel row count) an XLA recompile. This package is the request-level
serving path on top of the fitted estimators:

- :class:`EnsembleExecutor` (``executor.py``) — pre-compiles the
  aggregated ensemble forward once per power-of-two row bucket
  (``buckets.py``) with the input buffer donated; steady-state traffic
  runs compiled executables only (**zero recompiles after warmup**,
  counted by ``sbt_serving_compiles_total``).
- :class:`MicroBatcher` (``batcher.py``) — a bounded-queue background
  coalescer: concurrent ``submit()`` calls pack raggedly into the
  executor's slab plan (full ladder rungs, minimal padding) within a
  ``max_delay_ms``/``max_batch_rows`` window, with explicit
  :class:`Overloaded` backpressure and per-request futures; when a
  streak of singleton batches proves there is nobody to coalesce
  with, **adaptive direct dispatch** serves lone requests inline on
  the caller's thread (and hands back to the coalescer at the first
  sign of concurrency).
- :class:`ModelRegistry` (``registry.py``) — versioned registration
  and atomic hot-swap (``registry.swap(name, new_model)``), including
  load-from-checkpoint; swaps pre-compile the incoming executor on the
  live bucket set so traffic never sees a compile stall.
  ``registry.save()`` persists compiled bucket executables next to the
  weights (``aot_cache.py``) plus a ``serve_config.json`` manifest,
  and ``registry.load()`` hydrates both — a fresh serving process (or
  M peers behind a load balancer) comes up warm in the saver's exact
  version + executor config: zero compiles, no tracing,
  version-consistent rolling swaps.
- ``program_cache.py`` — the unified compiled-program cache every
  producer (batch predict, executor builds, AOT restores) shares: a
  program compiled anywhere is reused everywhere in the process.
- Mesh-sharded serving: ``EnsembleExecutor(model, mesh=...)`` shards
  the ensemble's replica axis across a ``(1, N)`` device mesh and
  serves outputs bitwise-identical to the single-device path (see
  ARCHITECTURE.md → Distributed serving).
- Fault tolerance end to end (see ARCHITECTURE.md → Fault tolerance):
  per-request deadlines (:class:`DeadlineExceeded`), bounded
  retry-with-backoff for transient forward failures, bisect-on-poison
  batch isolation, a supervised worker with crash-loop degraded
  reject mode (:class:`Degraded`, ``revive()``), rollback-safe
  ``swap()`` / torn-write-safe ``save()``, and degraded-quorum mesh
  serving (a failed shard drops out; the surviving-replica aggregate
  serves with ``degraded=true``) — all drillable deterministically
  via ``spark_bagging_tpu.faults`` and ``replay.py --chaos``.

Telemetry rides the PR-1 registry end to end: ``sbt_serving_*``
counters/gauges/histograms (requests, rows, batches, queue depth,
batch fill ratio, padding waste, compile count/seconds, request
latency, overload rejections, swap events) plus spans around
enqueue / forward / scatter.

Typical use::

    from spark_bagging_tpu.serving import ModelRegistry

    registry = ModelRegistry()
    registry.register("clf", fitted_model, warmup=True)
    batcher = registry.batcher("clf", max_delay_ms=2.0)

    fut = batcher.submit(x_row)          # from any thread
    proba = fut.result()

    registry.swap("clf", retrained)      # atomic, mid-traffic
    batcher.close()
"""

from spark_bagging_tpu.serving.batcher import (
    DeadlineExceeded,
    Degraded,
    MicroBatcher,
    Overloaded,
)
from spark_bagging_tpu.serving.buckets import (
    bucket_for,
    bucket_ladder,
    next_pow2,
    pack_plan,
    pad_to_bucket,
)
from spark_bagging_tpu.serving.executor import EnsembleExecutor
from spark_bagging_tpu.serving.registry import ModelRegistry

__all__ = [
    "DeadlineExceeded",
    "Degraded",
    "EnsembleExecutor",
    "MicroBatcher",
    "ModelRegistry",
    "Overloaded",
    "bucket_for",
    "bucket_ladder",
    "next_pow2",
    "pack_plan",
    "pad_to_bucket",
]
