"""Pre-compiled, shape-bucketed executor for one fitted ensemble.

The batch API (``BaggingClassifier.predict_proba`` &c.) re-enters jit
dispatch per call and compiles per novel input shape — fine for
offline scoring, wrong for online traffic. ``EnsembleExecutor`` turns
a fitted estimator into a long-lived predictor:

- the aggregated forward (``model.aggregated_forward()``) is lowered
  and compiled ONCE per row bucket (AOT, ``.lower().compile()``) with
  the incoming ``X`` buffer **donated** — steady state runs compiled
  executables only, no tracing, no dispatch-cache probing;
- incoming batches pad up to the power-of-two bucket ladder
  (``buckets.py``), so the compiled-shape set is finite and
  :meth:`warmup` makes post-warmup compiles exactly zero
  (``sbt_serving_compiles_total`` counts every build);
- batches larger than the top bucket split into top-bucket slabs.

Thread-safe: compiled executables are safe to call concurrently; the
bucket cache itself is built under a lock (one compile per bucket even
when many threads race to first use).
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from spark_bagging_tpu import faults, telemetry
from spark_bagging_tpu.analysis.locks import make_lock
from spark_bagging_tpu.telemetry import capacity as _capacity
from spark_bagging_tpu.telemetry import perf as _perf
from spark_bagging_tpu.telemetry import tracing
from spark_bagging_tpu.serving import program_cache as _pc
from spark_bagging_tpu.serving.buckets import (
    DEFAULT_MAX_ROWS,
    DEFAULT_MIN_ROWS,
    bucket_for,
    bucket_ladder,
    pack_plan,
)


def _compiled_cost(compiled: Any) -> dict[str, float | None]:
    """FLOPs / bytes-accessed for one compiled executable, from XLA's
    ``cost_analysis()``, normalized across jax vintages (plain dict in
    recent releases, per-device list-of-dict in 0.4.x). Best-effort:
    backends that report nothing yield ``None`` values — cost
    attribution degrades to rows, it never breaks a compile."""
    flops: float | None = None
    nbytes: float | None = None
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        if isinstance(analysis, dict):
            f = analysis.get("flops")
            b = analysis.get("bytes accessed")
            if f is not None and float(f) > 0:
                flops = float(f)
            if b is not None and float(b) > 0:
                nbytes = float(b)
    # sbt-lint: disable=swallowed-fault — best-effort cost instrumentation: absence degrades the padding-waste gauges to rows, it must never fail a compile
    except Exception:  # noqa: BLE001 — optional instrumentation only
        pass
    return {"flops": flops, "bytes": nbytes}


# sbt-lint: shared-state
class EnsembleExecutor:
    """Serve one fitted bagging estimator with bucketed AOT compiles.

    ``model`` is any fitted ``Bagging*``/``RandomForest*`` estimator
    (or anything exposing the same ``aggregated_forward()`` contract).
    ``donate_input=True`` donates the padded ``X`` buffer to each
    forward — it is a per-call scratch transfer, so XLA may reuse its
    memory for the outputs. The default (``None``) donates on
    accelerator backends only: CPU XLA does not implement donation and
    would warn on every bucket compile.

    ``mesh`` switches the executor to the replica-sharded serving
    program (``parallel/sharded.replica_sharded_serving``): the
    ensemble's stacked params are sharded over the mesh's ``replica``
    axis, each per-bucket compile partitions the per-replica forward
    across the whole slice, and the aggregate comes back replicated —
    bitwise-identical to the single-device executor (the parity tests'
    contract). The mesh must have data-axis size 1 and a replica axis
    that divides ``n_estimators``. Everything else — the bucket
    ladder, ragged packing, the batcher seam, the quality tap — is
    unchanged.
    """

    def __init__(
        self,
        model: Any,
        *,
        min_bucket_rows: int = DEFAULT_MIN_ROWS,
        max_batch_rows: int = DEFAULT_MAX_ROWS,
        donate_input: bool | None = None,
        mesh: Any = None,
    ):
        import jax

        if donate_input is None:
            donate_input = jax.default_backend() != "cpu"
        if min_bucket_rows < 1 or max_batch_rows < min_bucket_rows:
            raise ValueError(
                f"need 1 <= min_bucket_rows <= max_batch_rows, got "
                f"{min_bucket_rows}, {max_batch_rows}"
            )
        self.mesh = mesh
        self.mesh_shape = _pc.mesh_shape(mesh)
        self._n_shards: int | None = None
        if mesh is None:
            fn, params, subspaces = model.aggregated_forward()
            rep_fn = None
            self._x_sharding = None
        else:
            from spark_bagging_tpu.parallel.sharded import (
                replica_sharded_serving,
            )

            (fn, rep_fn, params, subspaces, self._x_sharding,
             n_shards) = replica_sharded_serving(model, mesh)
            self._n_shards = int(n_shards)
            telemetry.set_gauge("sbt_serving_shard_devices",
                                float(n_shards))
        # degraded-quorum state (mesh executors only): shards marked
        # failed, and the surviving replica indices the degraded
        # aggregate averages over (None while healthy). The flag reads
        # on the hot path are single-reference snapshots — benign
        self._failed_shards: set[int] = set()
        self._survivors: tuple[int, ...] | None = None
        self.model = model
        self.task: str = model.task
        self.n_features: int = int(model.n_features_in_)
        self.classes_ = getattr(model, "classes_", None)
        self.min_bucket_rows = int(min_bucket_rows)
        self.max_batch_rows = int(max_batch_rows)
        self._fn = fn
        self._params = params
        self._subspaces = subspaces
        self._donate = bool(donate_input)
        # program identity for the unified compiled-program cache
        # (program_cache.py) and the AOT disk cache: computed ONCE per
        # executor (it hashes every parameter byte). The placed params
        # hash identically to the estimator's own, so executor compiles
        # and batch-predict compiles of the same model share entries.
        try:
            self.fingerprint: str = _pc.fingerprint_model(model)
        except AttributeError:
            self.fingerprint = _pc.fingerprint_params(
                type(model), self.task, self.n_features, self.classes_,
                params, subspaces,
            )
        self._variant = _pc.forward_variant(model)
        self._replica_variant = _pc.forward_variant(model, "replica")
        self._compiled: dict[int, Any] = {}
        # bucket -> {"flops", "bytes"} from compiled.cost_analysis()
        # at build time (None values when the backend reports none):
        # the cost denominator that turns the padding-waste gauge from
        # rows into FLOPs
        self.bucket_costs: dict[int, dict[str, float | None]] = {}
        self._build_lock = make_lock("serving.executor.build")
        # model-quality tap (telemetry/quality.py): None until a
        # monitor is attached — the hot-path gate is ONE attribute
        # read, the zero-overhead-when-disabled contract
        self._quality = None
        self._quality_warned = False
        # per-replica forward for the disagreement tap: resolved and
        # compiled lazily per bucket on first sampled batch; its
        # compiles count in sbt_quality_disagreement_compiles_total,
        # NOT the serving compile counter — the zero-post-warmup-
        # compile gate is about the serving path, and the tap is not it.
        # Mesh executors resolve it EAGERLY: the sharded serving
        # program is built from the replica closure, so its gathered
        # twin comes from the same construction (and the lazy resolve
        # would hand back the unsharded single-device closure).
        self._replica_fn = rep_fn
        self._replica_compiled: dict[int, Any] = {}
        self._replica_unavailable = False
        # stamped by ModelRegistry on register/swap; standalone
        # executors serve as anonymous version None
        self.model_name: str | None = None
        self.model_version: int | None = None

    # -- compile management --------------------------------------------

    @property
    def compiled_buckets(self) -> tuple[int, ...]:
        """Buckets with a live executable (ascending)."""
        return tuple(sorted(self._compiled))

    def warmup(self, buckets=None) -> tuple[int, ...]:
        """Compile ahead of traffic. ``buckets=None`` covers the full
        ladder — afterwards NO request can trigger a compile. Returns
        the buckets this call installed (compiled, or adopted from the
        unified program cache when another consumer of this model's
        programs already paid the compile)."""
        if buckets is None:
            buckets = bucket_ladder(self.min_bucket_rows,
                                    self.max_batch_rows)
        built = []
        for b in buckets:
            b = bucket_for(int(b), self.min_bucket_rows,
                           self.max_batch_rows)
            if b not in self._compiled:
                self._build(b)
                built.append(b)
        return tuple(built)

    def _program_key(self, bucket: int, variant: str | None = None):
        """Unified-cache identity of this executor's program at one
        bucket (see :mod:`~spark_bagging_tpu.serving.program_cache`)."""
        return _pc.ProgramKey(
            self.fingerprint, variant or self._variant, int(bucket),
            self.mesh_shape, self._donate, *_pc.toolchain_id(),
        )

    def _example_x(self, bucket: int):
        """The example ``X`` argument a bucket compile lowers against —
        placed with the replicated request sharding on mesh executors
        (the compiled program's input contract)."""
        import jax
        import jax.numpy as jnp

        Xz = jnp.zeros((bucket, self.n_features), jnp.float32)
        if self._x_sharding is not None:
            Xz = jax.device_put(Xz, self._x_sharding)
        return Xz

    def _install(self, bucket: int, compiled: Any) -> None:
        """Record one bucket executable + its cost gauges (caller holds
        the build lock)."""
        cost = _compiled_cost(compiled)
        # sbt-lint: disable=shared-state-unlocked — every caller holds self._build_lock (_build/_adopt)
        self.bucket_costs[bucket] = cost
        if telemetry.enabled():
            labels = {"bucket": str(bucket)}
            if cost["flops"] is not None:
                telemetry.set_gauge("sbt_serving_bucket_cost_flops",
                                    cost["flops"], labels=labels)
            if cost["bytes"] is not None:
                telemetry.set_gauge("sbt_serving_bucket_cost_bytes",
                                    cost["bytes"], labels=labels)
        # sbt-lint: disable=shared-state-unlocked — under self._build_lock (see docstring)
        self._compiled[bucket] = compiled

    def _build(self, bucket: int):
        """Install the forward for one bucket: a unified-cache hit
        adopts the already-compiled program (a compile someone else —
        another executor for this model, a batch predict, an AOT
        restore — already paid); only a miss lowers and compiles,
        counting ``sbt_serving_compiles_total``. Serialized +
        double-checked so racing threads resolve each bucket once."""
        import jax

        with self._build_lock:
            fn = self._compiled.get(bucket)
            if fn is not None:
                return fn
            key = self._program_key(bucket)
            compiled = _pc.cache().get(key)
            if compiled is not None:
                self._install(bucket, compiled)
                return compiled
            t0 = time.perf_counter()
            with telemetry.span("serving_compile", bucket=bucket):
                jitted = jax.jit(
                    self._fn,
                    donate_argnums=(2,) if self._donate else (),
                )
                compiled = jitted.lower(
                    self._params, self._subspaces, self._example_x(bucket)
                ).compile()
            if self._failed_shards:
                # degraded-program builds are deliberate fault-response
                # cost, not steady-state serving compiles: the
                # zero-post-warmup-compile gate stays meaningful under
                # chaos
                telemetry.inc("sbt_serving_degraded_compiles_total")
            else:
                telemetry.inc("sbt_serving_compiles_total")
                name = getattr(self, "model_name", None)
                if name is not None:
                    # labeled twin: per-model compile attribution so a
                    # chaos drill can prove bystanders paid zero compiles
                    telemetry.inc("sbt_serving_compiles_total",
                                  labels={"model": str(name)})
            if self.mesh is not None and not self._failed_shards:
                telemetry.inc(
                    "sbt_shardmap_traces_total",
                    labels={"kind": "serving",
                            "mesh": "x".join(map(str, self.mesh_shape))},
                )
            telemetry.observe("sbt_serving_compile_seconds",
                              time.perf_counter() - t0)
            compiled = _pc.cache().put(key, compiled)
            self._install(bucket, compiled)
            return compiled

    def _adopt(self, bucket: int, compiled: Any) -> bool:
        """Install a deserialized executable for ``bucket`` (the AOT
        warm-start path — no lowering, no compile, not counted in
        ``sbt_serving_compiles_total``). The adopted program is also
        published to the unified cache, so a restore warms every OTHER
        consumer of this model's programs too. First installer wins;
        returns whether this call installed it."""
        with self._build_lock:
            if bucket in self._compiled:
                return False
            compiled = _pc.cache().put(self._program_key(bucket),
                                       compiled)
            self._install(bucket, compiled)
            return True

    def save_executables(self, path: str) -> tuple[int, ...]:
        """Persist every compiled bucket executable to directory
        ``path`` (see :mod:`spark_bagging_tpu.serving.aot_cache` for
        the key contract). Returns the buckets saved."""
        from spark_bagging_tpu.serving.aot_cache import save_executables

        return save_executables(self, path)

    def restore_executables(self, path: str) -> tuple[int, ...]:
        """Hydrate bucket executables from a directory written by
        :meth:`save_executables` — instant warm start. Silently
        restores nothing (and falls back to lowering on demand) when
        the cache is absent or was built under a different key (model
        fingerprint, bucket ladder, mesh shape, jax version, backend,
        device kind, donation). Returns the buckets restored."""
        from spark_bagging_tpu.serving.aot_cache import restore_executables

        return restore_executables(self, path)

    def release_programs(self) -> tuple[int, ...]:
        """Drop every compiled bucket executable — the tenant-demotion
        seam [ISSUE 17]. Executors hold their programs in-instance
        (cache eviction alone never frees them), so a residency policy
        that wants a cold model's device footprint gone must call
        THIS: the in-instance ladder and the replica twins are
        cleared, and the unified cache drops this fingerprint's
        entries (charged through the capacity plane's eviction seam).
        The executor stays fully serveable — the next request lowers
        on demand, or :meth:`restore_executables` re-adopts a
        persisted ladder with zero compiles. Returns the buckets
        released."""
        with self._build_lock:
            released = tuple(sorted(self._compiled))
            self._compiled.clear()
            self._replica_compiled.clear()
            self.bucket_costs.clear()
        _pc.cache().drop_fingerprint(self.fingerprint)
        if released:
            telemetry.inc("sbt_serving_programs_released_total",
                          float(len(released)))
        return released

    # -- degraded-quorum serving (mesh executors) ----------------------

    @property
    def degraded(self) -> bool:
        """True when this executor serves the surviving-replica
        aggregate after one or more mesh shards failed."""
        return bool(self._failed_shards)

    @property
    def failed_shards(self) -> tuple[int, ...]:
        return tuple(sorted(self._failed_shards))

    @property
    def surviving_replicas(self) -> int | None:
        """How many replicas the (degraded) aggregate averages over —
        None while healthy (every replica serves)."""
        return len(self._survivors) if self._survivors is not None else None

    def degrade_shards(self, shards) -> None:
        """Manually drop mesh shards from the serving quorum (the
        operator's version of what a :class:`faults.ShardFault` does
        automatically). Mesh executors only."""
        if self.mesh is None:
            raise ValueError(
                "degrade_shards is mesh-serving only; a single-device "
                "executor has no shards to lose"
            )
        for s in shards:
            self._degrade_shard(int(s))

    def _degrade_shard(self, shard: int) -> bool:
        """Drop ``shard`` from the quorum and swap the serving program
        to the surviving-replica aggregate (single-device, bitwise-
        equal to an offline recompute of the subset aggregate — see
        ``parallel/sharded.replica_subset_serving``). Returns whether
        this call newly degraded (False: shard already failed)."""
        from spark_bagging_tpu.parallel.sharded import (
            replica_subset_serving,
        )

        with self._build_lock:
            if self._n_shards is None or shard in self._failed_shards:
                return False
            if not 0 <= shard < self._n_shards:
                raise ValueError(
                    f"shard must be in [0, {self._n_shards}), got "
                    f"{shard}"
                )
            n_rep = int(self._subspaces.shape[0]) \
                if not self._failed_shards else len(self._all_replicas)
            if not self._failed_shards:
                # remember the healthy replica universe once: later
                # losses subset from IT, not from the already-shrunk
                # degraded params
                self._all_replicas = tuple(range(n_rep))
            per = len(self._all_replicas) // self._n_shards
            failed = self._failed_shards | {shard}
            survivors = [
                i for i in self._all_replicas if (i // per) not in failed
            ]
            if not survivors:
                raise RuntimeError(
                    "every serving shard has failed; no surviving "
                    "replicas left to aggregate"
                )
            fn, rep_fn, params, subspaces = replica_subset_serving(
                self.model, survivors
            )
            self._failed_shards.add(shard)
            self._survivors = tuple(survivors)
            self._fn = fn
            self._replica_fn = rep_fn
            self._replica_unavailable = False
            self._params = params
            self._subspaces = subspaces
            self._x_sharding = None
            tag = ",".join(map(str, sorted(self._failed_shards)))
            self._variant = (
                _pc.forward_variant(self.model)
                + f"|degraded-shards=[{tag}]"
            )
            self._replica_variant = (
                _pc.forward_variant(self.model, "replica")
                + f"|degraded-shards=[{tag}]"
            )
            # every compiled program belonged to the old quorum
            self._compiled.clear()
            self._replica_compiled.clear()
            self.bucket_costs.clear()
        import warnings

        telemetry.inc("sbt_serving_shard_failures_total")
        telemetry.set_gauge("sbt_serving_degraded", 1.0)
        telemetry.set_gauge("sbt_serving_degraded_replicas",
                            float(len(survivors)))
        telemetry.emit_event({
            "kind": "serving_shard_failed",
            "shard": shard,
            "failed_shards": sorted(self._failed_shards),
            "survivors": len(survivors),
            "model": self.model_name,
            "version": self.model_version,
        })
        warnings.warn(
            f"serving shard {shard} dropped from the quorum; serving "
            f"the {len(survivors)}-replica surviving aggregate "
            "(degraded=true) until reset_degraded()",
            RuntimeWarning,
            stacklevel=3,
        )
        return True

    def reset_degraded(self) -> bool:
        """Heal back to the full-quorum mesh program (the shard's
        device recovered, or a chaos run ended). Returns whether
        anything was reset."""
        with self._build_lock:
            if not self._failed_shards:
                return False
            from spark_bagging_tpu.parallel.sharded import (
                replica_sharded_serving,
            )

            (fn, rep_fn, params, subspaces, self._x_sharding,
             _n) = replica_sharded_serving(self.model, self.mesh)
            self._failed_shards.clear()
            self._survivors = None
            self._fn = fn
            self._replica_fn = rep_fn
            self._params = params
            self._subspaces = subspaces
            self._variant = _pc.forward_variant(self.model)
            self._replica_variant = _pc.forward_variant(
                self.model, "replica")
            self._compiled.clear()
            self._replica_compiled.clear()
            self.bucket_costs.clear()
        telemetry.set_gauge("sbt_serving_degraded", 0.0)
        telemetry.set_gauge("sbt_serving_degraded_replicas", 0.0)
        return True

    # -- model-quality tap ---------------------------------------------

    def attach_quality(self, monitor) -> None:
        """Install a quality monitor (see ``telemetry.quality.attach``,
        which also registers it for ``/debug/drift``). The forward
        feeds it per packed batch; ``None`` detaches."""
        # sbt-lint: disable=shared-state-unlocked — single-reference last-write-wins swap; the hot path reads it exactly once per batch
        self._quality = monitor
        # a FRESH monitor deserves a fresh failure warning: without
        # the reset, monitor B dying after monitor A already warned
        # would detach silently and the model would serve unmonitored
        # with zero operator signal
        # sbt-lint: disable=shared-state-unlocked — same benign last-write-wins as the monitor reference above
        self._quality_warned = False

    def detach_quality(self) -> None:
        # sbt-lint: disable=shared-state-unlocked — see attach_quality
        self._quality = None

    @property
    def quality(self):
        """The attached quality monitor, or None."""
        return self._quality

    def warmup_replica(self, buckets=None) -> tuple[int, ...]:
        """Compile the per-replica (disagreement-tap) forward ahead of
        traffic — default: every bucket the SERVING forward already
        has compiled. ``telemetry.quality.attach`` calls this when
        disagreement sampling is on (so sticky swap re-attaches do
        too): without it, the first sampled batch at each rung would
        absorb a full XLA compile stall on the live serving thread.
        Returns the buckets built (empty when the model exposes no
        per-replica seam)."""
        if buckets is None:
            buckets = self.compiled_buckets
        built = []
        for b in buckets:
            b = bucket_for(int(b), self.min_bucket_rows,
                           self.max_batch_rows)
            if b not in self._replica_compiled:
                if self._build_replica(b) is None:
                    break  # seam unavailable: nothing else will build
                built.append(b)
        return tuple(built)

    def _build_replica(self, bucket: int):
        """Compile the per-replica (aggregation-free) forward for one
        bucket — the disagreement tap's executable. Same double-checked
        build lock as :meth:`_build`; no donation (the tap re-reads the
        slab the serving forward already consumed). Returns None when
        the model exposes no per-replica seam."""
        import jax

        if self._replica_unavailable:
            return None
        with self._build_lock:
            fn = self._replica_compiled.get(bucket)
            if fn is not None:
                return fn
            if self._replica_fn is None:
                try:
                    self._replica_fn, _, _ = self.model.replica_forward()
                except (AttributeError, NotImplementedError) as e:
                    # sbt-lint: disable=shared-state-unlocked — under self._build_lock
                    self._replica_unavailable = True
                    import warnings

                    warnings.warn(
                        "ensemble-disagreement tap disabled: the model "
                        f"exposes no replica_forward() ({e!r})",
                        RuntimeWarning,
                        stacklevel=2,
                    )
                    return None
            key = self._program_key(bucket, self._replica_variant)
            compiled = _pc.cache().get(key)
            if compiled is None:
                with telemetry.span("quality_replica_compile",
                                    bucket=bucket):
                    jitted = jax.jit(self._replica_fn)
                    compiled = jitted.lower(
                        self._params, self._subspaces,
                        self._example_x(bucket)
                    ).compile()
                telemetry.inc("sbt_quality_disagreement_compiles_total")
                compiled = _pc.cache().put(key, compiled)
            self._replica_compiled[bucket] = compiled
            return compiled

    def _replica_piece(self, Xp: np.ndarray, fill: int):
        """Per-replica output for one slab's real rows — ``(R, fill,
        C)`` / ``(R, fill)`` — or None when the seam is unavailable."""
        bucket = Xp.shape[0]
        compiled = self._replica_compiled.get(bucket)
        if compiled is None:
            compiled = self._build_replica(bucket)
            if compiled is None:
                return None
        out = np.asarray(compiled(self._params, self._subspaces, Xp))
        return out[:, :fill]

    def _feed_quality(self, mon, parts, outs, first_slab) -> None:
        """Deliver one packed batch to the attached monitor (sketches
        + sampled disagreement). Monitoring faults must never fail the
        serving it observes: first failure warns and detaches."""
        try:
            mon.observe_parts(parts, outs)
            if first_slab is not None and mon.wants_disagreement():
                rep = self._replica_piece(*first_slab)
                if rep is not None:
                    mon.observe_disagreement(rep, task=self.task)
        except Exception as e:  # noqa: BLE001 — the tap is optional
            # sbt-lint: disable=shared-state-unlocked — last-write-wins detach on failure; racing feeders at worst both detach
            self._quality = None
            if not self._quality_warned:
                # sbt-lint: disable=shared-state-unlocked — worst case under a race is a second warning, never a lost detach
                self._quality_warned = True
                import warnings

                warnings.warn(
                    f"quality monitor detached after a tap failure: "
                    f"{e!r}",
                    RuntimeWarning,
                    stacklevel=2,
                )

    # -- the forward ---------------------------------------------------

    def _validate(self, X) -> np.ndarray:
        X = np.ascontiguousarray(X, dtype=np.float32)
        if X.ndim == 1:
            # single feature vector: the overwhelmingly common online
            # request shape — accept it as one row
            X = X[None, :]
        if X.ndim != 2 or X.shape[1] != self.n_features:
            raise ValueError(
                f"X must be (n, {self.n_features}), got {X.shape}"
            )
        if X.shape[0] == 0:
            raise ValueError("X has no rows")
        return X

    def forward(self, X) -> np.ndarray:
        """Aggregated output for ``X`` — (n, C) probabilities for a
        classifier, (n,) predictions for a regressor. Rows run through
        the ragged pack plan (:func:`~spark_bagging_tpu.serving.
        buckets.pack_plan`): full ladder rungs first, only the final
        slab padded, padding sliced off before anything is returned."""
        X = self._validate(X)
        (out,) = self._forward_packed([X])
        return out

    __call__ = forward

    def forward_parts(self, parts) -> list[np.ndarray]:
        """Ragged batch: serve several independent row blocks as ONE
        packed forward sequence and return one output per block.

        The blocks are packed back-to-back into the pack plan's slabs
        with a row-offset scatter — no intermediate concatenation, no
        per-block padding: each row is copied into device-transfer
        memory exactly once, and only the final slab carries padding.
        A block may span a slab boundary; bagging aggregation is
        row-local, so its rows' results are unaffected by which slab
        (or which batch-mates) they rode with — served outputs stay
        bitwise-equal to the batch ``predict``/``predict_proba`` of
        each block alone. This is the micro-batcher's scatter seam.
        """
        if not parts:
            return []
        return self._forward_packed([self._validate(p) for p in parts])

    def _forward_packed(self, parts: list[np.ndarray]) -> list[np.ndarray]:
        """Pack validated row blocks into plan slabs, run each slab,
        scatter outputs back per block."""
        sizes = [p.shape[0] for p in parts]
        n = sum(sizes)
        plan = pack_plan(n, self.min_bucket_rows, self.max_batch_rows)
        # gather: walk the blocks once, filling each slab in order;
        # only the last slab is partial (pack_plan's fill rule)
        slab_outs: list[np.ndarray] = []
        first_slab: tuple[np.ndarray, int] | None = None
        part_i = 0
        part_off = 0
        remaining = n
        for bucket in plan:
            fill = min(bucket, remaining)
            remaining -= fill
            part = parts[part_i]
            if fill == bucket and part.shape[0] - part_off >= fill:
                # the whole slab comes from one block: serve the slice
                # as-is (a view — zero-copy, the fast path for the
                # single-request forward and for large blocks)
                Xp = part[part_off:part_off + fill]
                part_off += fill
                if part_off == part.shape[0]:
                    part_i += 1
                    part_off = 0
            else:
                # row-offset scatter: one zeroed slab buffer, each
                # block's rows copied in at its offset (this replaces
                # concatenate-then-pad, which copied every row twice)
                Xp = np.zeros((bucket, self.n_features), np.float32)
                off = 0
                while off < fill:
                    part = parts[part_i]
                    take = min(fill - off, part.shape[0] - part_off)
                    Xp[off:off + take] = part[part_off:part_off + take]
                    off += take
                    part_off += take
                    if part_off == part.shape[0]:
                        part_i += 1
                        part_off = 0
            if first_slab is None:
                # kept for the (sampled) disagreement tap: one slab per
                # packed batch is the tap's unit of work
                first_slab = (Xp, fill)
            while True:
                try:
                    slab_outs.append(self._forward_piece(Xp, fill))
                    break
                except faults.ShardFault as e:
                    # a mesh shard failed mid-forward: drop it from
                    # the quorum and re-serve this slab through the
                    # surviving-replica aggregate. Each loop iteration
                    # fails a NEW shard (bounded by the shard count);
                    # a fault naming an already-failed shard is not a
                    # new loss and propagates as an ordinary error
                    if self.mesh is None or not self._degrade_shard(
                            e.shard):
                        raise
        # scatter back: slice each block's rows out of the slab outputs
        # (views when a block sat inside one slab; boundary-spanning
        # blocks concatenate their pieces)
        outs: list[np.ndarray] = []
        slab_i = 0
        slab_off = 0
        for size in sizes:
            pieces: list[np.ndarray] = []
            need = size
            while need:
                out = slab_outs[slab_i]
                take = min(need, out.shape[0] - slab_off)
                pieces.append(out[slab_off:slab_off + take])
                need -= take
                slab_off += take
                if slab_off == out.shape[0]:
                    slab_i += 1
                    slab_off = 0
            outs.append(pieces[0] if len(pieces) == 1
                        else np.concatenate(pieces))
        # model-quality tap: one attribute read when no monitor is
        # attached (the zero-overhead contract). This seam sits under
        # BOTH dispatch paths — the coalescing worker's forward_parts
        # and the direct-dispatch inline serve — and feeds real rows
        # only (padding never reaches the sketches). Outputs are
        # already finalized above: the tap cannot change what is served.
        mon = self._quality
        if mon is not None:
            self._feed_quality(mon, parts, outs, first_slab)
        # capacity demand tap [ISSUE 16]: same one-attribute-read
        # contract as the quality tap and faults.ACTIVE — unarmed cost
        # is this single module-attribute load. Feeds per-model
        # request/row demand under BOTH dispatch paths; anonymous
        # executors (model_name unset — never registry-committed) stay
        # out of the demand table by design.
        cap = _capacity.ACTIVE
        if cap is not None and self.model_name is not None:
            cap.observe_demand(self.model_name, self.model_version,
                               len(parts), n)
        return outs

    # sbt-lint: hot-path
    def _forward_piece(self, Xp: np.ndarray, fill: int) -> np.ndarray:
        """Run one bucket-shaped slab (``fill`` real rows, the rest
        padding) through its compiled executable; returns the real
        rows' output."""
        bucket = Xp.shape[0]
        if faults.ACTIVE is not None:
            # chaos probes (one module-attribute read when unarmed):
            # generic slab faults, plus the per-shard mesh-forward
            # seam that simulates losing a device mid-traffic
            faults.fire("executor.forward_piece", bucket=bucket)
            if self.mesh is not None and not self._failed_shards:
                faults.fire("executor.mesh_forward", bucket=bucket)
        degraded = bool(self._failed_shards)
        compiled = self._compiled.get(bucket)
        if compiled is None:
            compiled = self._build(bucket)
        if telemetry.enabled():
            counts = [
                ("sbt_serving_rows_total", float(fill)),
                ("sbt_serving_padding_rows_total", float(bucket - fill)),
            ]
            if self.mesh is not None and not degraded:
                counts.append(("sbt_serving_shard_forwards_total", 1.0))
            if degraded:
                counts.append(("sbt_serving_degraded_forwards_total",
                               1.0))
            flops = self.bucket_costs.get(bucket, {}).get("flops")
            if flops:
                # rows are interchangeable within a bucket's program,
                # so padding's FLOP share is its row share — waste in
                # compute terms, not just rows
                counts.append(("sbt_serving_flops_total", flops))
                counts.append(("sbt_serving_padding_flops_total",
                               (bucket - fill) / bucket * flops))
            # one registry lock round-trip for the whole panel: this
            # runs per slab on the request hot path
            telemetry.inc_many(counts)
            telemetry.observe("sbt_serving_batch_fill_ratio",
                              fill / bucket)
        # attach the bucket choice to whatever request/batch trace is
        # current (multi-slab packs annotate once per slab)
        tracing.annotate(bucket=bucket)
        # performance-attribution probe (telemetry/perf.py): measured
        # per-bucket forward seconds joined with the compile-time cost
        # gauges. The faults.ACTIVE pattern — one module-attribute
        # read when no plane is installed, no clock, no call
        ap = _perf.ACTIVE
        t_perf = time.perf_counter() if ap is not None else 0.0
        if telemetry.sinks_active():
            with telemetry.span("serving_forward", bucket=bucket,
                                rows=fill):
                out = compiled(self._params, self._subspaces, Xp)
                # sbt-lint: disable=host-sync-in-span — the served result must reach the host here; the span times the true forward latency
                out = np.asarray(out)
        else:
            # nobody is listening for span events (no open capture, no
            # armed recorder, no scrape server): skip the span
            # machinery — it was a measurable slice of the direct
            # path's per-request budget
            out = np.asarray(compiled(self._params, self._subspaces, Xp))
        if ap is not None:
            ap.observe_forward(bucket, fill,
                               time.perf_counter() - t_perf,
                               self.bucket_costs.get(bucket))
        return out[:fill]

    # -- sklearn-flavored conveniences ---------------------------------

    def predict_proba(self, X) -> np.ndarray:
        if self.task != "classification":
            raise AttributeError(
                "predict_proba is classification-only; this executor "
                f"serves a {self.task} model"
            )
        return self.forward(X)

    def predict(self, X) -> np.ndarray:
        out = self.forward(X)
        if self.task == "classification":
            return self.classes_[out.argmax(axis=1)]
        return out
