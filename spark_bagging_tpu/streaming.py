"""Out-of-core ensemble training over chunked data streams.

The reference reaches Criteo scale by leaving the data distributed in
Spark partitions and shipping the fit to executors [SURVEY §1 L1]; the
TPU-native equivalent streams fixed-shape host chunks into HBM and runs
ONE compiled optimizer step per chunk, with every replica's bootstrap
weights regenerated on-device from ``(seed, chunk_id, replica_id)``
[SURVEY §7 step 8, hard-part 4].

Why this is exact bagging: the Poisson bootstrap factorizes over rows
[P:5], so a replica's weight for row j depends only on the key — not on
any other row. Keying the draw by the chunk's id makes weights
*epoch-stable*: revisiting chunk c in any later epoch regenerates
exactly the same weights, so the stream fit optimizes a fixed weighted
objective, chunk by chunk (stochastic gradient over chunks).

The jitted step donates the carried ``(params, opt_state)`` buffers, so
ensemble state stays resident in HBM across the whole stream; only the
current chunk crosses host→device per step.

Sharding: with a ``(data, replica)`` mesh the chunk's rows are placed
sharded over ``data`` and every params leaf over ``replica`` (leading
axis); the step body is sharding-agnostic (weight draws don't depend on
device layout), so XLA's SPMD partitioner inserts the collectives —
the ``pjit`` path, no hand-written ``shard_map`` needed here.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from contextlib import closing
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_bagging_tpu import telemetry
from spark_bagging_tpu.parallel.multihost import global_put, to_host

from spark_bagging_tpu.models.base import BaseLearner
from spark_bagging_tpu.ops.bootstrap import (
    RNG_SCHEMA,
    bootstrap_weights_one,
    feature_subspaces,
    replica_init_fit_keys,
)
from spark_bagging_tpu.parallel.mesh import DATA_AXIS, REPLICA_AXIS
from spark_bagging_tpu.utils.io import ChunkSource

_EPS = 1e-8
# Independent stream tag for chunk-keyed row draws (cf. ops/bootstrap.py
# stream tags; distinct so streaming and in-memory fits don't collide).
_CHUNK_STREAM = 0xC4C


def split_aux_col(
    Xc, aux_col: int | None
) -> tuple[np.ndarray, np.ndarray | None]:
    """Host-side aux-column split — the ONE place the column-drop
    convention lives, shared by the fit loop and the OOB pass so the
    two can never disagree on the feature layout. Returns
    ``(X_without_aux, aux_or_None)``; both float32."""
    Xc = np.asarray(Xc, np.float32)
    if aux_col is None:
        return Xc, None
    return np.delete(Xc, aux_col % Xc.shape[1], axis=1), Xc[:, aux_col]


def _shard_ensemble(tree: Any, mesh) -> Any:
    """Place every array leaf sharded over the replica mesh axis on its
    leading (replica) axis; scalar leaves (e.g. Adam step counts stacked
    per replica are 1-D, true scalars stay replicated)."""
    def put(leaf):
        leaf = jnp.asarray(leaf)
        if leaf.ndim == 0:
            return global_put(leaf, mesh, P())
        spec = P(REPLICA_AXIS, *([None] * (leaf.ndim - 1)))
        return global_put(leaf, mesh, spec)
    return jax.tree.map(put, tree)


def _save_stream_checkpoint(
    path: str, params, opt_state, losses, meta: dict
) -> None:
    """Atomic snapshot of the stream-fit state [SURVEY §5 checkpoint,
    VERDICT r1 #7]: write to a temp dir, then rename into place, so a
    kill mid-save leaves the previous snapshot intact.

    Multihost: the ``to_host`` gathers are collective — EVERY process
    must reach this function each snapshot — but only process 0 touches
    the filesystem (the shared-storage single-writer convention; PIDs
    collide across hosts and concurrent renames of one path race), so
    ``checkpoint_dir`` must be on storage all hosts can read for
    ``resume_from`` to work pod-wide."""
    from flax import serialization  # lazy: keep flax off the import path

    tree = {
        # to_host: params/opt_state are P(replica) global arrays on a
        # mesh and may span processes (multihost stream fits)
        "params": jax.tree.map(to_host, params),
        "opt_state": serialization.to_state_dict(
            jax.tree.map(to_host, opt_state)
        ),
        # losses arrive pre-gathered (the caller keeps a host mirror,
        # extended incrementally — re-gathering the whole list per
        # snapshot was quadratic in chunk count)
        "final_epoch_losses": (
            np.stack([np.asarray(l) for l in losses])
            if losses else np.zeros((0, 0), np.float32)
        ),
    }
    save_snapshot(path, tree, meta)


def learner_fingerprint(learner: BaseLearner) -> str:
    """Stable hyperparameter fingerprint for resume-config and
    warm-start validation (shared by the SGD and tree stream
    checkpointers and bagging's warm-start guard). Built on the SAME
    canonical key as ``BaseLearner.__hash__``/``__eq__`` so jit-cache
    identity and fingerprint identity can never diverge."""
    # list(...) preserves the historical string format (repr of a
    # sorted LIST of pairs) so pre-existing stream checkpoints keep
    # resuming across this refactor
    return repr(list(learner._params_key())) + type(learner).__qualname__


def check_resume_config(meta: dict, config: dict, path: str) -> None:
    """A resumed run must be continuing THIS fit: raise with the
    mismatched keys if the snapshot's config fingerprint differs."""
    saved = meta.get("config", {})
    if saved != config:
        diff = {
            k for k in set(saved) | set(config)
            if saved.get(k) != config.get(k)
        }
        raise ValueError(
            f"checkpoint at {path} was written by a different fit "
            f"configuration (mismatched: {sorted(diff)})"
        )


def save_snapshot(path: str, tree: Any, meta: dict) -> None:
    """Atomically install a (msgpack pytree, JSON meta) snapshot at
    ``path`` — the shared mechanism for every stream checkpointer.
    Single-writer: non-0 processes return before touching the FS."""
    from flax import serialization

    if jax.process_index() != 0:
        return
    import glob

    tmp = f"{path}.tmp.{os.getpid()}"
    # reap multi-GB tmp debris left by DEAD processes' mid-write kills
    # (pid-liveness gated, exactly as utils/checkpoint.py does)
    for stale in glob.glob(glob.escape(path) + ".tmp.*"):
        suffix = stale.rsplit(".", 1)[1]
        if stale == tmp or not suffix.isdigit() or not os.path.isdir(stale):
            continue
        try:
            os.kill(int(suffix), 0)
        except ProcessLookupError:
            shutil.rmtree(stale, ignore_errors=True)
        except PermissionError:
            pass
    os.makedirs(tmp, exist_ok=True)
    with telemetry.span("checkpoint_save",
                        metric="sbt_checkpoint_seconds"):
        payload = serialization.msgpack_serialize(tree)
        with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
            f.write(payload)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2)
    telemetry.inc("sbt_checkpoint_bytes_total", float(len(payload)),
                  labels={"kind": "stream", "op": "save"})
    # Never leave a window with no valid snapshot: move the previous
    # one aside, install the new one, then drop the old. A kill between
    # the two renames leaves `path.old`, which load falls back to.
    # After a PRIOR mid-swap crash (`path` missing, `path.old` the only
    # survivor), `.old` must outlive everything until the new snapshot
    # is INSTALLED — rmtree'ing it up front would reopen the
    # zero-valid-snapshot window this dance exists to close.
    old = f"{path}.old"
    if os.path.isdir(path):
        if os.path.isdir(old):
            shutil.rmtree(old)  # `path` is intact: the slot is stale
        os.replace(path, old)
        os.replace(tmp, path)
        shutil.rmtree(old)
    else:
        os.replace(tmp, path)
        if os.path.isdir(old):
            shutil.rmtree(old)  # superseded by the snapshot just installed


def _load_stream_checkpoint(path: str) -> tuple[dict, dict]:
    from flax import serialization

    if not os.path.isdir(path) and os.path.isdir(f"{path}.old"):
        path = f"{path}.old"  # crashed between the two snapshot renames
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    with open(os.path.join(path, "state.msgpack"), "rb") as f:
        tree = serialization.msgpack_restore(f.read())
    return meta, tree


def fit_ensemble_stream(
    learner: BaseLearner,
    source: ChunkSource,
    key: jax.Array,
    n_replicas: int,
    n_outputs: int,
    *,
    n_epochs: int = 1,
    steps_per_chunk: int = 1,
    lr: float = 0.01,
    sample_ratio: float = 1.0,
    bootstrap: bool = True,
    n_subspace: int | None = None,
    bootstrap_features: bool = False,
    mesh=None,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 0,
    resume_from: str | None = None,
    aux_col: int | None = None,
) -> tuple[Any, jax.Array, dict[str, Any]]:
    """Fit all replicas by streaming chunks from ``source``.

    Returns ``(stacked_params, subspaces, aux)`` exactly like
    ``fit_ensemble`` — the fitted ensemble is indistinguishable
    downstream (predict/persistence) from an in-memory fit.

    ``aux_col`` designates one column of the streamed feature block as
    the per-row auxiliary channel (the Spark censorCol-as-a-column
    convention): each chunk splits it off host-side before the device
    step, so EVERY source (CSV, Arrow, hashed, synthetic, arrays)
    carries aux with zero format changes. Requires a ``uses_aux``
    learner (e.g. AFTSurvivalRegression); the model then expects
    aux-free feature vectors at predict time.

    Fault tolerance [SURVEY §5 failure detection, VERDICT r1 #7]:
    ``checkpoint_dir`` + ``checkpoint_every=N`` snapshot
    ``(params, opt_state, cursor, final-epoch losses)`` atomically every
    N chunk-steps; ``resume_from`` restores a snapshot and replays the
    deterministic chunk stream from the saved cursor — a resumed fit is
    bit-identical to the uninterrupted one (chunk-keyed weight draws
    don't depend on wall-clock or visit order). The snapshot's config
    fingerprint must match the current call (validated, clear error).
    """
    if not learner.streamable:
        raise TypeError(
            f"{type(learner).__name__} does not support streaming fits "
            "(no row_loss/penalty); use an SGD-capable learner or the "
            "in-memory fit"
        )
    if checkpoint_dir is not None and checkpoint_every <= 0:
        raise ValueError(
            "checkpoint_dir is set but checkpoint_every is 0 — no "
            "snapshot would ever be written; pass checkpoint_every=N"
        )
    if aux_col is not None and not learner.uses_aux:
        raise ValueError(
            f"aux_col was passed but {type(learner).__name__} does not "
            "declare uses_aux (the column would be silently dropped)"
        )
    n_features = source.n_features - (1 if aux_col is not None else 0)
    if aux_col is not None:
        if not (-source.n_features <= aux_col < source.n_features):
            raise ValueError(
                f"aux_col={aux_col} out of range for "
                f"{source.n_features} streamed columns"
            )
        # normalize once so -1 and n-1 fingerprint as the SAME fit
        # (resume compatibility) and every downstream split agrees
        aux_col = aux_col % source.n_features
    elif learner.uses_aux:
        import warnings

        warnings.warn(
            f"{type(learner).__name__} consumes a per-row aux column "
            "but the stream carries none (aux_col=None): every row is "
            "treated as fully observed. If the censor indicator is a "
            "column of the stream, pass aux_col=<index> — otherwise it "
            "is being fit as an ordinary feature.", UserWarning,
        )
    chunk_rows = source.chunk_rows
    if n_subspace is None:
        n_subspace = n_features
    identity_subspace = n_subspace == n_features and not bootstrap_features
    ids = jnp.arange(n_replicas, dtype=jnp.int32)
    subspaces = feature_subspaces(
        key, ids, n_features, n_subspace, replacement=bootstrap_features
    )
    row_key = jax.random.fold_in(key, _CHUNK_STREAM)

    def init_one(rid):
        init_key, _ = replica_init_fit_keys(key, rid)
        return learner.init_params(init_key, n_subspace, n_outputs)

    params = jax.vmap(init_one)(ids)
    opt = optax.adam(lr)
    opt_state = jax.vmap(opt.init)(params)

    # Config fingerprint: a resumed run must be continuing THIS fit.
    config = {
        "key": np.asarray(jax.random.key_data(key)).tolist(),
        "n_replicas": n_replicas,
        "n_outputs": n_outputs,
        "n_epochs": n_epochs,
        "steps_per_chunk": steps_per_chunk,
        "lr": lr,
        "sample_ratio": sample_ratio,
        "bootstrap": bootstrap,
        "n_subspace": n_subspace,
        "bootstrap_features": bootstrap_features,
        "chunk_rows": chunk_rows,
        "n_features": n_features,
        # stream length is part of the fit's identity: resuming against
        # a shorter/longer source would silently skip (or double-visit)
        # chunks while passing every other check (round-4 audit)
        "n_rows": source.n_rows,
        "n_chunks": source.n_chunks,
        # bootstrap RNG schema: the round-4 _ROW_STREAM retag changed
        # every weight draw, so a pre-retag snapshot must not resume
        # under the new scheme (it would splice each replica from two
        # different bootstrap samples); absent key == schema 1 == reject
        "rng_schema": RNG_SCHEMA,
        "aux_col": aux_col,
        "learner": learner_fingerprint(learner),
    }

    start_epoch, start_chunk = 0, 0
    final_epoch_losses: list[jax.Array] = []
    # host-side mirror of final_epoch_losses, extended lazily at
    # snapshot time: re-gathering the whole list per snapshot was
    # O(n_chunks²/checkpoint_every) device syncs (round-4 audit)
    host_losses: list[np.ndarray] = []
    if resume_from is not None:
        from flax import serialization

        meta, tree = _load_stream_checkpoint(resume_from)
        # pre-aux_col snapshots lack the key; absent == None (the
        # default) so old checkpoints resume cleanly. Snapshots written
        # before entry-point normalization may carry a negative index —
        # normalize it the same way so -1 and n-1 compare equal.
        saved_cfg = meta.setdefault("config", {})
        saved_cfg.setdefault("aux_col", None)
        if saved_cfg["aux_col"] is not None:
            saved_cfg["aux_col"] %= source.n_features
        # pre-round-4 snapshots predate stream-length validation:
        # accept them at the current source's values (no worse than
        # their own era), so only NEW snapshots enforce the length
        saved_cfg.setdefault("n_rows", source.n_rows)
        saved_cfg.setdefault("n_chunks", source.n_chunks)
        check_resume_config(meta, config, resume_from)
        params = serialization.from_state_dict(params, tree["params"])
        opt_state = serialization.from_state_dict(
            opt_state, tree["opt_state"]
        )
        start_epoch, start_chunk = meta["epoch"], meta["next_chunk"]
        final_epoch_losses = [
            jnp.asarray(l) for l in tree["final_epoch_losses"]
        ]
        host_losses = [np.asarray(l) for l in tree["final_epoch_losses"]]
    # Learners pin MXU matmul precision (the TPU bf16-default hazard —
    # see models/logistic.py); the streamed gradient steps honor the
    # same knob.
    precision = getattr(learner, "precision", "highest")

    if mesh is not None:
        data_size = mesh.shape.get(DATA_AXIS, 1)
        replica_size = mesh.shape.get(REPLICA_AXIS, 1)
        if n_replicas % replica_size != 0:
            raise ValueError(
                f"n_replicas={n_replicas} not divisible by replica mesh "
                f"axis {replica_size}"
            )
        if chunk_rows % data_size != 0:
            raise ValueError(
                f"chunk_rows={chunk_rows} not divisible by data mesh "
                f"axis {data_size}"
            )
        params = _shard_ensemble(params, mesh)
        opt_state = _shard_ensemble(opt_state, mesh)
        subspaces = _shard_ensemble(subspaces, mesh)
        x_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
        y_sharding = NamedSharding(mesh, P(DATA_AXIS))
    else:
        x_sharding = y_sharding = None

    y_dtype = jnp.int32 if learner.task == "classification" else jnp.float32

    use_aux = aux_col is not None

    # one fixed signature: aux is None (a leafless pytree under jit)
    # when the stream carries no aux column
    def chunk_step(params, opt_state, X, y, aux_arr, n_valid, chunk_uid):
        valid = (jnp.arange(chunk_rows) < n_valid).astype(jnp.float32)
        chunk_key = jax.random.fold_in(row_key, chunk_uid)

        with jax.default_matmul_precision(precision):
            return _chunk_body(
                params, opt_state, X, y, aux_arr, valid, chunk_key
            )

    def _chunk_body(params, opt_state, X, y, aux_arr, valid, chunk_key):

        def one(p, os, rid, idx):
            w = bootstrap_weights_one(
                chunk_key, rid, chunk_rows,
                ratio=sample_ratio, replacement=bootstrap,
            ) * valid
            Xs = X if identity_subspace else X[:, idx]

            def loss_fn(p):
                rl = (
                    learner.row_loss(p, Xs, y, aux=aux_arr)
                    if use_aux else learner.row_loss(p, Xs, y)
                )
                data = jnp.sum(w * rl)
                data = data / jnp.maximum(jnp.sum(w), _EPS)
                return data + learner.penalty(p)

            # several optimizer steps per chunk visit: amortizes the
            # host->device transfer and the weight draw (weights are
            # fixed for the visit — the objective doesn't change)
            def opt_step(carry, _):
                p, os = carry
                loss, g = jax.value_and_grad(loss_fn)(p)
                updates, os = opt.update(g, os, p)
                return (optax.apply_updates(p, updates), os), loss

            (p, os), losses = jax.lax.scan(
                opt_step, (p, os), None, length=steps_per_chunk
            )
            return p, os, losses[-1]

        return jax.vmap(one)(params, opt_state, ids, subspaces)

    # donate carried state so the ensemble lives in HBM in place
    chunk_step = jax.jit(chunk_step, donate_argnums=(0, 1))

    n_chunks = source.n_chunks
    t0 = time.perf_counter()
    compile_seconds = None
    steps_done = 0
    for epoch in range(start_epoch, n_epochs):
        telemetry.inc("sbt_stream_epochs_total", labels={"engine": "sgd"})
        # resume seeks straight to the cursor (O(1) on random-access
        # sources; discard-scan elsewhere) instead of re-ingesting and
        # dropping every pre-cursor chunk; `closing` makes prefetch
        # teardown deterministic when a chunk step raises
        offset = start_chunk if epoch == start_epoch else 0
        seen = offset - 1
        with closing(source.chunks_from(offset)) as chunk_iter:
          for c, (Xc, yc, n_valid) in enumerate(chunk_iter, start=offset):
            seen = c
            # per-chunk span: wall-clock of transfer + step dispatch
            # (device-sync opt-in makes it the true step latency); the
            # histogram is the chunk-latency distribution BENCH reads
            with telemetry.span(
                "chunk_step", metric="sbt_chunk_seconds",
                epoch=epoch, chunk=c,
            ):
                Xc, auxc = split_aux_col(Xc, aux_col)
                if x_sharding is not None:
                    # host chunk → ONE global placement (multihost-safe:
                    # every process streams the same chunks, each
                    # transfers only its shards — the broadcast-data
                    # design [B:5])
                    Xd = jax.device_put(Xc, x_sharding)
                    # sbt-lint: disable=host-sync-in-span — dtype cast of a host numpy chunk, not a device pull
                    yd = jax.device_put(np.asarray(yc, y_dtype), y_sharding)
                    auxd = (
                        jax.device_put(auxc, y_sharding) if use_aux
                        else None
                    )
                else:
                    Xd = jnp.asarray(Xc)
                    yd = jnp.asarray(yc, y_dtype)
                    auxd = jnp.asarray(auxc) if use_aux else None
                params, opt_state, losses = chunk_step(
                    params, opt_state, Xd, yd, auxd,
                    jnp.asarray(n_valid, jnp.int32),
                    jnp.asarray(c, jnp.int32),
                )
            telemetry.inc("sbt_stream_chunks_total",
                          labels={"engine": "sgd"})
            if compile_seconds is None:
                jax.block_until_ready(losses)
                compile_seconds = time.perf_counter() - t0
            if epoch == n_epochs - 1:
                final_epoch_losses.append(losses)
            steps_done += 1
            if (
                checkpoint_dir is not None
                and checkpoint_every > 0
                and steps_done % checkpoint_every == 0
            ):
                nxt_epoch, nxt_chunk = epoch, c + 1
                if nxt_chunk >= n_chunks:
                    nxt_epoch, nxt_chunk = epoch + 1, 0
                # gather only losses recorded since the last snapshot
                # (the to_host calls are collective: every process
                # appends identically, so the mirrors stay in step)
                host_losses.extend(
                    to_host(l)
                    for l in final_epoch_losses[len(host_losses):]
                )
                _save_stream_checkpoint(
                    checkpoint_dir, params, opt_state, host_losses,
                    {
                        "config": config,
                        "epoch": nxt_epoch,
                        "next_chunk": nxt_chunk,
                        "steps_done": steps_done,
                    },
                )
        # the declared n_chunks drives the resume cursor's epoch
        # rollover; a source that yields a different count than it
        # declares would silently skip or double-visit chunks across a
        # resume — fail the fit loudly instead (round-4 audit)
        if seen + 1 != n_chunks:
            raise ValueError(
                f"source yielded {seen + 1 - offset} chunk(s) for an "
                f"epoch spanning chunks [{offset}, {n_chunks}) — it "
                f"declares n_chunks={n_chunks} (n_rows={source.n_rows}, "
                f"chunk_rows={chunk_rows}); a miscounted source breaks "
                "checkpoint-resume exactness"
            )
    if not final_epoch_losses:
        raise ValueError("source yielded no chunks")
    # per-replica mean over the final epoch's chunks (reporting only)
    loss = jnp.stack(final_epoch_losses).mean(axis=0)
    aux = {
        "loss": loss,
        "n_chunks": n_chunks,
        "n_epochs": n_epochs,
        "stream_seconds": time.perf_counter() - t0,
        "first_step_seconds": compile_seconds,
        # optimizer steps actually executed THIS call (a resumed fit
        # counts only its own steps) — the honest-accounting basis for
        # the stream FLOPs model [VERDICT r2 ask#6]
        "opt_steps": steps_done * steps_per_chunk,
        "chunk_rows": chunk_rows,
    }
    return params, subspaces, aux


def oob_scores_stream(
    learner: BaseLearner,
    source: ChunkSource,
    key: jax.Array,
    stacked_params: Any,
    subspaces: jax.Array,
    n_replicas: int,
    *,
    sample_ratio: float = 1.0,
    bootstrap: bool = True,
    n_classes: int | None = None,
    chunk_size: int | None = None,
    identity_subspace: bool = False,
    aux_col: int | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """OOB aggregation for a streamed fit: ONE extra pass over the
    source [SURVEY §4, closing VERDICT r1 #3's fit_stream carve-out].
    ``aux_col`` (an aux-carrying stream, see fit_ensemble_stream) is
    dropped from each chunk before the predict — the fitted model's
    feature space excludes it.

    Works because chunk-keyed weight draws are epoch-stable: both stream
    engines (SGD and level-synchronous trees) draw chunk ``c``'s weights
    from ``fold_in(fold_in(key, _CHUNK_STREAM), c)``, so regenerating
    them here replays each replica's exact membership, and ``w == 0``
    rows are its out-of-bag rows — the same contract as the in-memory
    ``oob_predict_scores``.

    RESTRICTION: the replay assumes the fit drew from the GLOBAL chunk
    stream. A tree stream fitted over a mesh with ``data`` sharding > 1
    folds the shard index into each draw and draws per-shard-length
    weight vectors — this function cannot replay those, and calling it
    for such a fit would return silently wrong (optimistically biased)
    OOB memberships. Callers must reject that combination up front, as
    ``BaggingClassifier.fit_stream`` does.

    Returns ``(agg, n_votes, y)`` over all valid rows in stream order:
    ``agg`` is vote counts ``(n, C)`` for classification or prediction
    sums ``(n,)`` for regression; rows with ``n_votes == 0`` have no
    OOB estimate.
    """
    from spark_bagging_tpu.ensemble import map_replicas, oob_replica_contrib

    row_key = jax.random.fold_in(key, _CHUNK_STREAM)
    chunk_rows = source.chunk_rows
    ids = jnp.arange(n_replicas, dtype=jnp.int32)
    precision = getattr(learner, "precision", "highest")

    @jax.jit
    def chunk_oob(params, subs, X, n_valid, chunk_uid):
        valid = (jnp.arange(chunk_rows) < n_valid).astype(jnp.float32)
        chunk_key = jax.random.fold_in(row_key, chunk_uid)

        def one(args):
            p, idx, rid = args
            with jax.default_matmul_precision(precision):
                return oob_replica_contrib(
                    learner, p, idx, rid, X, chunk_key,
                    sample_ratio=sample_ratio, bootstrap=bootstrap,
                    n_classes=n_classes,
                    identity_subspace=identity_subspace,
                    extra_mask=valid,
                )

        contrib, votes = map_replicas(one, (params, subs, ids), chunk_size)
        return contrib.sum(axis=0), votes.sum(axis=0)

    aggs, votes_all, ys = [], [], []
    with closing(source.chunks()) as chunk_iter:
        for c, (Xc, yc, n_valid) in enumerate(chunk_iter):
            Xc, _ = split_aux_col(Xc, aux_col)
            a, v = chunk_oob(
                stacked_params, subspaces, jnp.asarray(Xc, jnp.float32),
                jnp.asarray(n_valid, jnp.int32), jnp.asarray(c, jnp.int32),
            )
            aggs.append(np.asarray(a)[:n_valid])
            votes_all.append(np.asarray(v)[:n_valid])
            ys.append(np.asarray(yc)[:n_valid])
    return (
        np.concatenate(aggs),
        np.concatenate(votes_all),
        np.concatenate(ys),
    )
