// Native data loader: libsvm / CSV -> dense float32 matrices.
//
// The reference's ingestion rides Spark's native-accelerated IO stack
// (Tungsten row memory, JNI codecs) [SURVEY §2b]; this is the
// TPU-native framework's equivalent: a small C++ parser behind a C ABI,
// loaded from Python via ctypes (utils/native.py), feeding host numpy
// buffers that jax.device_put ships to HBM [B:5]. Python parsers in
// utils/datasets.py remain as the portable fallback.
//
// Two access patterns:
//  - whole-file: *_dims() then *_fill() into caller-allocated buffers;
//  - streaming:  reader_open()/reader_next()/reader_close() yields
//    fixed-size row blocks for the out-of-core engine (utils/io.py).
//
// All functions return 0 on success, negative error codes otherwise.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

constexpr int kErrOpen = -1;
constexpr int kErrParse = -2;
constexpr int kErrArg = -3;
// the file holds an embedded NUL byte: every parser here works on
// NUL-terminated line buffers, which would silently truncate the row
// and diverge from the Python fallback (round-4 audit) — surface a
// distinct code so the ctypes layer can fall back to the Python
// parsers instead of mis-ingesting
constexpr int kErrNul = -4;

// fast float parse: strtof handles inf/nan/exponents; we just wrap it
inline bool parse_float(const char*& p, float* out) {
  char* end = nullptr;
  *out = strtof(p, &end);
  if (end == p) return false;
  p = end;
  return true;
}

inline void skip_ws(const char*& p) {
  while (*p == ' ' || *p == '\t' || *p == '\r') ++p;
}

struct LineReader {
  FILE* f = nullptr;
  char* buf = nullptr;
  size_t cap = 0;
  bool nul = false;  // an embedded NUL byte ended iteration

  explicit LineReader(const char* path) { f = fopen(path, "rb"); }
  ~LineReader() {
    if (f) fclose(f);
    free(buf);
  }
  bool ok() const { return f != nullptr; }
  // returns nullptr at EOF or on an embedded NUL (check `nul`);
  // strips trailing newline
  const char* next() {
    if (!f || nul) return nullptr;
    ssize_t n = getline(&buf, &cap, f);
    if (n < 0) return nullptr;
    while (n > 0 && (buf[n - 1] == '\n' || buf[n - 1] == '\r')) buf[--n] = 0;
    if (memchr(buf, 0, static_cast<size_t>(n)) != nullptr) {
      nul = true;  // parsers are NUL-terminated-string based: bail
      return nullptr;
    }
    return buf;
  }
};

// CRC-32 (IEEE 802.3), bit-identical to Python's zlib.crc32(data, crc):
// the hashed-CSV reader must produce the same slots/signs as the Python
// FeatureHasher (utils/hashing.py) or native and fallback ingestion
// would silently train on different features.
inline uint32_t crc32_update(uint32_t crc, const char* buf, size_t len) {
  static const auto table = [] {
    std::vector<uint32_t> t(256);
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
    return t;
  }();
  crc ^= 0xFFFFFFFFu;
  for (size_t i = 0; i < len; ++i)
    crc = table[(crc ^ static_cast<uint8_t>(buf[i])) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

// per-categorical-column memo: value -> (slot, sign); size-capped like
// the Python FeatureHasher (Criteo columns reach 10M+ uniques)
constexpr size_t kMemoCap = 1u << 20;

// transparent hashing so memo probes take a string_view — the hot
// ingestion loop must not heap-allocate a std::string per categorical
// field just to check the memo (C++20 heterogeneous lookup)
struct SvHash {
  using is_transparent = void;
  size_t operator()(std::string_view sv) const noexcept {
    return std::hash<std::string_view>{}(sv);
  }
};
struct SvEq {
  using is_transparent = void;
  bool operator()(std::string_view a, std::string_view b) const noexcept {
    return a == b;
  }
};

struct HashedSpec {
  std::vector<int64_t> numeric, categorical;
  int64_t n_hash = 0;
  uint32_t seed = 0;
  char delim = ',';
  int64_t max_col = 0;
  std::vector<std::unordered_map<std::string, std::pair<int64_t, float>,
                                 SvHash, SvEq>>
      memo;
};

// does the line hold anything besides whitespace/comment?
inline bool svm_line_nonempty(const char* line) {
  const char* p = line;
  skip_ws(p);
  return *p != 0 && *p != '#';
}

// parse one libsvm line into y + (idx, val) writes on a dense row
inline int svm_parse_line(const char* line, float* y, float* row,
                          int64_t n_features, int zero_based) {
  const char* p = line;
  skip_ws(p);
  if (!parse_float(p, y)) return kErrParse;
  while (true) {
    skip_ws(p);
    if (*p == 0 || *p == '#') break;
    char* end = nullptr;
    long idx = strtol(p, &end, 10);
    if (end == p || *end != ':') return kErrParse;
    p = end + 1;
    float val;
    if (!parse_float(p, &val)) return kErrParse;
    int64_t j = zero_based ? idx : idx - 1;
    if (j >= 0 && j < n_features) row[j] = val;
  }
  return 0;
}

// parse one CSV line of exactly n_cols floats into dst; trailing
// content (extra columns, trailing commas) is a parse error so ragged
// files fail loudly, matching the numpy fallback
inline int csv_parse_line(const char* line, float* dst, int64_t n_cols) {
  const char* p = line;
  for (int64_t c = 0; c < n_cols; ++c) {
    skip_ws(p);
    if (!parse_float(p, &dst[c])) return kErrParse;
    skip_ws(p);
    if (c + 1 < n_cols) {
      if (*p != ',') return kErrParse;
      ++p;
    }
  }
  skip_ws(p);
  if (*p != 0) return kErrParse;
  return 0;
}

struct Reader {
  LineReader lr;
  int fmt;  // 0 = libsvm, 1 = csv, 2 = hashed csv
  int64_t n_features = 0;
  int64_t n_cols = 0;  // csv: total columns incl. label
  int64_t label_col = -1;
  int zero_based = 0;
  HashedSpec* hspec = nullptr;

  Reader(const char* path, int fmt_) : lr(path), fmt(fmt_) {}
  ~Reader() { delete hspec; }
};

// split a line on spec.delim into (start, len) fields
inline void split_fields(const char* line, char delim,
                         std::vector<std::pair<const char*, size_t>>* out) {
  out->clear();
  const char* start = line;
  const char* p = line;
  for (;; ++p) {
    if (*p == delim || *p == 0) {
      out->emplace_back(start, static_cast<size_t>(p - start));
      if (*p == 0) break;
      start = p + 1;
    }
  }
}

// float() parity with the Python fallback: surrounding whitespace ok,
// anything else trailing is an error; empty field -> 0 handled by the
// caller. strtof extensions Python rejects are rejected here too
// (C99 hex floats); underscored literals are rejected on BOTH paths
// (the fallback mirrors this) so native and Python never diverge.
inline bool parse_field_float(const char* s, size_t len, float* out) {
  // stack buffer: numeric fields are short, and the hot path must not
  // heap-allocate per field; oversized fields take the slow copy
  char stack[64];
  std::string heap;
  const char* p;
  if (len < sizeof(stack)) {
    std::memcpy(stack, s, len);
    stack[len] = 0;
    p = stack;
  } else {
    heap.assign(s, len);
    p = heap.c_str();
  }
  for (size_t i = 0; i < len; ++i)
    if (p[i] == 'x' || p[i] == 'X' || p[i] == '_') return false;
  char* end = nullptr;
  *out = strtof(p, &end);
  if (end == p) return false;
  while (*end == ' ' || *end == '\t') ++end;
  return *end == 0;
}

// one hashed-CSV row: numeric passthrough + signed-hash accumulation.
// xrow must be zeroed by the caller (signs ACCUMULATE into slots).
inline int hashed_parse_row(
    HashedSpec* h,
    const std::vector<std::pair<const char*, size_t>>& fields,
    int64_t label_col, float* xrow, float* y) {
  if (static_cast<int64_t>(fields.size()) <= h->max_col) return kErrParse;
  auto [lp, ll] = fields[label_col];
  if (ll == 0) {
    *y = 0.0f;
  } else if (!parse_field_float(lp, ll, y)) {
    return kErrParse;
  }
  for (size_t j = 0; j < h->numeric.size(); ++j) {
    auto [fp, fl] = fields[h->numeric[j]];
    if (fl == 0) {
      xrow[j] = 0.0f;  // empty field -> 0, the Criteo convention
    } else if (!parse_field_float(fp, fl, &xrow[j])) {
      return kErrParse;
    }
  }
  float* hash_base = xrow + h->numeric.size();
  for (size_t j = 0; j < h->categorical.size(); ++j) {
    auto [fp, fl] = fields[h->categorical[j]];
    std::string_view value(fp, fl);  // no allocation on memo hits
    auto& memo = h->memo[j];
    auto it = memo.find(value);
    int64_t slot;
    float sign;
    if (it != memo.end()) {
      slot = it->second.first;
      sign = it->second.second;
    } else {
      // token layout matches utils/hashing.py: "<j>=<value>" where j
      // is the position within the categorical list
      std::string token = std::to_string(j);
      token += '=';
      token.append(value.data(), value.size());
      slot = crc32_update(h->seed, token.data(), token.size()) % h->n_hash;
      token.push_back('#');
      sign = (crc32_update(h->seed, token.data(), token.size()) & 1)
                 ? 1.0f : -1.0f;
      if (memo.size() < kMemoCap) memo.emplace(std::string(value),
                                               std::make_pair(slot, sign));
    }
    hash_base[slot] += sign;
  }
  return 0;
}

}  // namespace

extern "C" {

// ---- whole-file libsvm -------------------------------------------------

// rows and 1-based max feature index (0 if none)
int svm_dims(const char* path, int zero_based, int64_t* n_rows,
             int64_t* max_feature) {
  LineReader lr(path);
  if (!lr.ok()) return kErrOpen;
  int64_t rows = 0, maxf = 0;
  while (const char* line = lr.next()) {
    if (!svm_line_nonempty(line)) continue;
    ++rows;
    const char* p = line;
    skip_ws(p);
    float dummy;
    if (!parse_float(p, &dummy)) return kErrParse;
    while (true) {
      skip_ws(p);
      if (*p == 0 || *p == '#') break;
      char* end = nullptr;
      long idx = strtol(p, &end, 10);
      if (end == p || *end != ':') return kErrParse;
      p = end + 1;
      float val;
      if (!parse_float(p, &val)) return kErrParse;
      int64_t j = zero_based ? idx + 1 : idx;
      if (j > maxf) maxf = j;
    }
  }
  if (lr.nul) return kErrNul;
  *n_rows = rows;
  *max_feature = maxf;
  return 0;
}

// fill pre-allocated X (n_rows * n_features, zeroed) and y (n_rows)
int svm_fill(const char* path, int zero_based, int64_t n_rows,
             int64_t n_features, float* X, float* y) {
  if (!X || !y || n_features <= 0) return kErrArg;
  LineReader lr(path);
  if (!lr.ok()) return kErrOpen;
  int64_t i = 0;
  while (const char* line = lr.next()) {
    if (!svm_line_nonempty(line)) continue;
    if (i >= n_rows) break;
    int rc = svm_parse_line(line, &y[i], &X[i * n_features], n_features,
                            zero_based);
    if (rc != 0) return rc;
    ++i;
  }
  if (lr.nul) return kErrNul;
  return i == n_rows ? 0 : kErrParse;
}

// non-blank data-line count (hashed-CSV n_rows pass; no float parsing,
// so categorical columns are fine)
int64_t csv_count_rows(const char* path, int skip_header) {
  LineReader lr(path);
  if (!lr.ok()) return kErrOpen;
  int64_t n = 0;
  bool skipped = !skip_header;
  while (const char* line = lr.next()) {
    const char* p = line;
    skip_ws(p);
    if (*p == 0) continue;
    if (!skipped) {
      skipped = true;
      continue;
    }
    ++n;
  }
  if (lr.nul) return kErrNul;
  return n;
}

// ---- whole-file csv ----------------------------------------------------

int csv_dims(const char* path, int skip_header, int64_t* n_rows,
             int64_t* n_cols) {
  LineReader lr(path);
  if (!lr.ok()) return kErrOpen;
  int64_t rows = 0, cols = 0;
  bool first = true;
  while (const char* line = lr.next()) {
    const char* p = line;
    skip_ws(p);
    if (*p == 0) continue;
    if (first) {
      cols = 1;
      for (const char* q = line; *q; ++q)
        if (*q == ',') ++cols;
      first = false;
      if (skip_header) continue;
    }
    ++rows;
  }
  if (lr.nul) return kErrNul;
  *n_rows = rows;
  *n_cols = cols;
  return 0;
}

// fill X (n_rows * (n_cols-1)) and y (n_rows); label_col may be negative
// (python-style, counted from the end)
int csv_fill(const char* path, int skip_header, int64_t label_col,
             int64_t n_rows, int64_t n_cols, float* X, float* y) {
  if (!X || !y || n_cols < 2) return kErrArg;
  int64_t lc = label_col < 0 ? label_col + n_cols : label_col;
  if (lc < 0 || lc >= n_cols) return kErrArg;
  LineReader lr(path);
  if (!lr.ok()) return kErrOpen;
  float* tmp = static_cast<float*>(malloc(sizeof(float) * n_cols));
  if (!tmp) return kErrArg;
  int64_t i = 0;
  bool first = true;
  while (const char* line = lr.next()) {
    const char* p = line;
    skip_ws(p);
    if (*p == 0) continue;
    if (first) {
      first = false;
      if (skip_header) continue;
    }
    if (i >= n_rows) break;
    int rc = csv_parse_line(line, tmp, n_cols);
    if (rc != 0) {
      free(tmp);
      return rc;
    }
    float* xrow = &X[i * (n_cols - 1)];
    int64_t xj = 0;
    for (int64_t c = 0; c < n_cols; ++c) {
      if (c == lc)
        y[i] = tmp[c];
      else
        xrow[xj++] = tmp[c];
    }
    ++i;
  }
  free(tmp);
  if (lr.nul) return kErrNul;
  return i == n_rows ? 0 : kErrParse;
}

// ---- streaming reader --------------------------------------------------

void* reader_open_svm(const char* path, int64_t n_features,
                      int zero_based) {
  Reader* r = new Reader(path, 0);
  if (!r->lr.ok()) {
    delete r;
    return nullptr;
  }
  r->n_features = n_features;
  r->zero_based = zero_based;
  return r;
}

void* reader_open_csv(const char* path, int64_t n_cols, int64_t label_col,
                      int skip_header) {
  Reader* r = new Reader(path, 1);
  if (!r->lr.ok()) {
    delete r;
    return nullptr;
  }
  r->n_cols = n_cols;
  r->n_features = n_cols - 1;
  r->label_col = label_col < 0 ? label_col + n_cols : label_col;
  // An out-of-range label column would make the per-row column split in
  // reader_next write n_cols floats into an (n_cols-1)-wide X row —
  // refuse at open time instead (csv_fill applies the same check).
  if (n_cols < 2 || r->label_col < 0 || r->label_col >= n_cols) {
    delete r;
    return nullptr;
  }
  if (skip_header) {
    // discard the first NON-BLANK line, mirroring csv_dims: a leading
    // blank line must not absorb the skip and leave the header in the
    // data stream
    while (const char* line = r->lr.next()) {
      const char* p = line;
      skip_ws(p);
      if (*p != 0) break;
    }
  }
  return r;
}

void* reader_open_csv_hashed(const char* path, int64_t label_col,
                             const int64_t* numeric, int64_t n_numeric,
                             const int64_t* categorical, int64_t n_cat,
                             int64_t n_hash, int64_t seed, char delim,
                             int skip_header) {
  if (label_col < 0 || n_hash < 2 || (n_numeric <= 0 && n_cat <= 0))
    return nullptr;
  Reader* r = new Reader(path, 2);
  if (!r->lr.ok()) {
    delete r;
    return nullptr;
  }
  auto* h = new HashedSpec;
  h->numeric.assign(numeric, numeric + n_numeric);
  h->categorical.assign(categorical, categorical + n_cat);
  h->n_hash = n_hash;
  h->seed = static_cast<uint32_t>(seed);
  h->delim = delim;
  h->max_col = label_col;
  for (int64_t c : h->numeric) {
    if (c < 0) { delete h; delete r; return nullptr; }
    if (c > h->max_col) h->max_col = c;
  }
  for (int64_t c : h->categorical) {
    if (c < 0) { delete h; delete r; return nullptr; }
    if (c > h->max_col) h->max_col = c;
  }
  h->memo.resize(h->categorical.size());
  r->hspec = h;
  r->label_col = label_col;
  r->n_features = n_numeric + (n_cat > 0 ? n_hash : 0);
  if (skip_header) {
    while (const char* line = r->lr.next()) {
      const char* p = line;
      skip_ws(p);
      if (*p != 0) break;
    }
  }
  return r;
}

// reads up to max_rows rows into X (max_rows * n_features, caller-zeroed
// for libsvm and hashed csv) and y; returns rows read (0 at EOF) or a
// negative error
int64_t reader_next(void* handle, int64_t max_rows, float* X, float* y) {
  Reader* r = static_cast<Reader*>(handle);
  if (!r || !X || !y) return kErrArg;
  float* tmp = nullptr;
  if (r->fmt == 1) {
    tmp = static_cast<float*>(malloc(sizeof(float) * r->n_cols));
    if (!tmp) return kErrArg;
  }
  int64_t i = 0;
  while (i < max_rows) {
    const char* line = r->lr.next();
    if (!line) break;
    const char* p = line;
    skip_ws(p);
    if (*p == 0) continue;
    if (r->fmt == 0) {
      if (!svm_line_nonempty(line)) continue;
      int rc = svm_parse_line(line, &y[i], &X[i * r->n_features],
                              r->n_features, r->zero_based);
      if (rc != 0) return rc;
    } else if (r->fmt == 2) {
      static thread_local std::vector<std::pair<const char*, size_t>>
          fields;
      split_fields(line, r->hspec->delim, &fields);
      int rc = hashed_parse_row(r->hspec, fields, r->label_col,
                                &X[i * r->n_features], &y[i]);
      if (rc != 0) return rc;
    } else {
      int rc = csv_parse_line(line, tmp, r->n_cols);
      if (rc != 0) {
        free(tmp);
        return rc;
      }
      float* xrow = &X[i * r->n_features];
      int64_t xj = 0;
      for (int64_t c = 0; c < r->n_cols; ++c) {
        if (c == r->label_col)
          y[i] = tmp[c];
        else
          xrow[xj++] = tmp[c];
      }
    }
    ++i;
  }
  free(tmp);
  if (r->lr.nul) return kErrNul;
  return i;
}

void reader_close(void* handle) { delete static_cast<Reader*>(handle); }

}  // extern "C"
