"""Native C++ sources (compiled on demand by utils/native.py).

This package exists so ``loader.cpp`` ships with the distribution
(``[tool.setuptools.package-data]`` maps package names, not bare
directories).
"""
